"""obs/ — unified tracing, metrics registry, and profiler capture.

The acceptance pins:

1. A traced run is BIT-IDENTICAL to an untraced run — params and every
   logged row — on the fused and the sharded (client_shards=2 reference)
   paths: the tracer only reads host clocks, never RNG or device state.
2. The exporter emits valid Chrome-trace JSON (ph/ts/dur/pid/tid fields,
   thread_name metadata naming the tracks).
3. A served run's trace shows LINKED submission->merge spans (same
   r<rnd>/c<cid> id as the admission instants) plus distinct prepare/
   dispatch/drain/commit phases per round.
4. The registry is thread-safe under the ingest path and is the single
   source RunStats is carved from (mark deltas).
5. The jax.profiler window starts/stops at the right round boundaries and
   degrades to a LOUD no-op where the profiler is unavailable.
6. TableLogger's JSONL sink survives a SIGKILLed process with only whole
   JSON lines on disk (crash-safe observability is table stakes).
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

import cv_train
from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated.api import FederatedSession, FedOptimizer
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.obs import registry as obreg
from commefficient_tpu.obs import trace as obtrace
from commefficient_tpu.obs.profiler import ProfileWindow, parse_rounds_spec
from commefficient_tpu.runner import RunnerConfig, run_loop
from commefficient_tpu.serve import (
    AggregationService, IngestQueue, ServeConfig, Submission, TraceConfig,
    TrafficGenerator,
)

LR = 0.05


@pytest.fixture(autouse=True)
def _disarm_tracer():
    """Every test leaves the global tracer disarmed (configure() with no
    paths resets the buffer and disables emission)."""
    yield
    obtrace.configure()


@pytest.fixture()
def tiny_cv(tmp_path, monkeypatch):
    import flax.linen as nn

    import commefficient_tpu.data.cifar as cifar_mod

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=64, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)

    class _TinyNet(nn.Module):
        num_classes: int = 10
        dtype: str = "float32"

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(self.num_classes)(x)

    monkeypatch.setattr(cv_train, "ResNet9", _TinyNet)
    return tmp_path


def _argv(extra=()):
    return [
        "--dataset", "cifar10", "--mode", "uncompressed", "--num_clients", "8",
        "--num_workers", "2", "--local_batch_size", "4", "--lr_scale", "0.05",
        "--weight_decay", "0", "--data_root", "/nonexistent", *extra,
    ]


def _quad_loss(params, net_state, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
    mask = batch["mask"]
    count = jnp.maximum(mask.sum(), 1.0)
    per_ex = (err ** 2).sum(-1)
    return (per_ex * mask).sum() / count, {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()}}


def _tiny_session(shards=0, seed=0, num_clients=12, workers=4, din=6, dout=3):
    rs = np.random.RandomState(0)
    x = rs.randn(96, din).astype(np.float32)
    w_true = rs.randn(din, dout).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int32)
    train = FedDataset(x, y, shard_iid(len(x), num_clients,
                                       np.random.RandomState(1)))
    params = {"w": jnp.asarray(rs.randn(din, dout).astype(np.float32) * 0.1),
              "b": jnp.zeros(dout)}
    d = ravel_pytree(params)[0].size
    return FederatedSession(
        train_loss_fn=_quad_loss, eval_loss_fn=_quad_loss,
        params=params, net_state={},
        mode_cfg=ModeConfig(mode="uncompressed", d=d, momentum=0.9,
                            momentum_type="virtual", error_type="none"),
        train_set=train, num_workers=workers, local_batch_size=4,
        seed=seed, client_shards=shards,
    )


def _assert_params_equal(sa, sb):
    for x, y in zip(
        jax.tree.leaves(jax.device_get(sa.state["params"])),
        jax.tree.leaves(jax.device_get(sb.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _rows(path):
    rows = [json.loads(line) for line in open(path)]
    for r in rows:
        r.pop("time_s")
    return rows


# ------------------------------------------------- THE bit-identity pins


@pytest.mark.parametrize("shards", [0, 2], ids=["fused", "sharded"])
def test_traced_rounds_bit_identical_to_untraced(shards, tmp_path):
    """Tracing reads host clocks only: round metrics and final params of a
    traced session must equal an untraced one's to the last bit — fused
    AND on the sharded single-device reference program."""
    a = _tiny_session(shards=shards)
    rows_a = [a.run_round(LR) for _ in range(3)]

    obtrace.configure(trace_path=str(tmp_path / "t.json"),
                      jsonl_path=str(tmp_path / "ev.jsonl"))
    b = _tiny_session(shards=shards)
    rows_b = [b.run_round(LR) for _ in range(3)]
    obtrace.configure()

    assert rows_a == rows_b
    _assert_params_equal(a, b)


@pytest.mark.chaos
def test_traced_cli_run_bit_identical_to_untraced(tiny_cv, tmp_path):
    """Full CLI run (async runner, eval cadence mid-run) with --trace +
    --trace_events vs without: params and every logged JSONL row must be
    bit-identical, and the trace must land with runner spans in it."""
    base = _argv(("--num_rounds", "4", "--eval_every", "2"))
    la, lb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    trace_path = str(tmp_path / "run_trace.json")
    sa = cv_train.main(base + ["--log_jsonl", la])
    sb = cv_train.main(base + ["--log_jsonl", lb, "--trace", trace_path,
                               "--trace_events",
                               str(tmp_path / "ev.jsonl")])
    assert sa.round == sb.round == 4
    _assert_params_equal(sa, sb)
    assert _rows(la) == _rows(lb)
    ev = json.load(open(trace_path))["traceEvents"]
    names = {e["name"] for e in ev if e["ph"] == "X"}
    assert {"prepare", "dispatch", "drain", "commit", "eval"} <= names
    # the federated prepare span ran on the prefetch thread and still landed
    assert "prepare_round" in names


# ----------------------------------------------------- exporter schema


def test_chrome_trace_export_schema(tmp_path):
    path = str(tmp_path / "t.json")
    obtrace.configure(trace_path=path)
    with obtrace.span("runner", "phase", round=0):
        pass
    obtrace.instant("resilience", "fault:test", round=1)
    obtrace.complete("device", "rounds 0..0", obtrace.now_us(), 123.0,
                     rounds=1)
    out = obtrace.flush()
    assert out == path
    doc = json.load(open(path))
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    assert {e["ph"] for e in evs} <= {"X", "i", "M"}
    for e in evs:
        assert {"ph", "pid", "tid", "name"} <= set(e), e
        if e["ph"] in ("X", "i"):
            assert isinstance(e["ts"], (int, float))
            assert "args" in e and "cat" in e
        if e["ph"] == "X":
            assert e["dur"] >= 0
    track_names = {e["args"]["name"] for e in evs
                   if e["ph"] == "M" and e["name"] == "thread_name"}
    assert {"runner", "device", "writer", "serve-ingest", "assembler",
            "federated", "resilience"} <= track_names
    # instants keep their args (the chaos smoke greps rounds out of these)
    inst = [e for e in evs if e["ph"] == "i"]
    assert inst and inst[0]["args"]["round"] == 1


def test_jsonl_event_sink_schema_and_whole_lines(tmp_path):
    path = tmp_path / "events.jsonl"
    obtrace.configure(jsonl_path=str(path))
    with obtrace.span("runner", "drain", rounds=2):
        pass
    obtrace.instant("federated", "requeue_serve", round=3, clients=[1])
    obtrace.configure()  # closes the sink
    lines = path.read_text().splitlines()
    assert len(lines) == 2
    for line in lines:
        ev = json.loads(line)
        assert ev["schema"] == obtrace.EVENT_SCHEMA_VERSION
        assert ev["track"] in ("runner", "federated")
        assert "ts" in ev and "name" in ev


def test_jsonl_stream_outlives_buffer_cap(tmp_path, capsys):
    """The bounded in-memory buffer caps the Chrome trace, not the on-disk
    JSONL stream: past max_events the stream keeps writing and the first
    drop is announced loudly (a --trace_events-only run never reaches
    flush()'s dropped-events note)."""
    path = tmp_path / "ev.jsonl"
    t = obtrace.Tracer(max_events=2)
    t.configure(trace_path=str(tmp_path / "t.json"), jsonl_path=str(path))
    for i in range(5):
        t.instant("runner", f"e{i}")
    assert t.event_count() == 2 and t.dropped_events == 3
    assert len(path.read_text().splitlines()) == 5
    assert "trace buffer full" in capsys.readouterr().err


def test_tracer_disabled_is_noop_and_bounded(tmp_path):
    t = obtrace.Tracer(max_events=3)
    with t.span("runner", "x"):
        pass
    t.instant("runner", "y")
    assert t.event_count() == 0  # disarmed: nothing buffered
    t.configure(trace_path=str(tmp_path / "t.json"))
    for i in range(10):
        t.instant("runner", f"e{i}")
    assert t.event_count() == 3  # bounded buffer
    assert t.dropped_events == 7
    doc = json.load(open(t.flush()))
    assert doc["otherData"]["dropped_events"] == 7


# ------------------------------------------- serve: linked merge spans


def test_serve_trace_links_submissions_and_shows_round_phases(tmp_path):
    """4-round served run through the REAL runner (sync loop => every
    round drains): the trace must show prepare/dispatch/drain/commit per
    round, submission->merge spans linked to their admission instants by
    the r<rnd>/c<cid> id, and the /metrics snapshot must surface the
    latency_ms / round_phase_ms histograms — the PR's acceptance shape."""
    obtrace.configure(trace_path=str(tmp_path / "serve.json"))
    sess = _tiny_session()
    svc = AggregationService(
        sess, ServeConfig(quorum=2, deadline_s=5.0),
        traffic=TrafficGenerator(
            TraceConfig(population=sess.train_set.num_clients, seed=5)),
    ).start()
    lat_before = svc._latency.count
    try:
        run_loop(sess, FedOptimizer(lambda _: LR, 1),
                 RunnerConfig(total_rounds=4, eval_every=4, sync_loop=True),
                 source=svc.source())
        assert sess.round == 4
        snap = svc.metrics_snapshot()
    finally:
        svc.close()
    evs = obtrace.get().events()
    spans = [e for e in evs if e["ph"] == "X"]
    inst = [e for e in evs if e["ph"] == "i"]
    sub_spans = [s for s in spans if s["name"].startswith("submission r")]
    for r in range(4):
        assert any(s["name"] == "prepare" and s["args"].get("round") == r
                   for s in spans), f"round {r}: no prepare span"
        assert any(s["name"] == "dispatch" and s["args"].get("round") == r
                   for s in spans), f"round {r}: no dispatch span"
        for phase in ("drain", "commit"):
            assert any(
                s["name"] == phase
                and s["args"]["round_first"] <= r
                < s["args"]["round_first"] + s["args"]["rounds"]
                for s in spans), f"round {r}: no {phase} span"
        assert any(i_["name"] == "commit_round"
                   and i_["args"]["round"] == r for i_ in inst)
        assert any(s["args"]["round"] == r for s in sub_spans), (
            f"round {r}: no submission->merge span")
    # linked: every merge span's submission id appeared as an ACCEPT
    accept_ids = {i_["args"]["submission"] for i_ in inst
                  if i_["name"] == "submit:ACCEPTED"}
    merge_ids = {s["args"]["submission"] for s in sub_spans}
    assert merge_ids and merge_ids <= accept_ids
    assert all(s["dur"] >= 0 for s in sub_spans)
    # the registry histogram counted exactly the merged submissions
    assert svc._latency.count - lat_before == len(sub_spans)
    # /metrics reads the same registry
    assert snap["latency_ms"]["count"] >= len(sub_spans)
    assert snap["latency_ms"]["p50"] is not None
    for phase in ("prepare", "dispatch", "drain", "commit"):
        assert snap["round_phase_ms"][phase]["p50"] is not None, phase


def test_fresh_service_does_not_claim_predecessor_merges():
    """The latency histogram is process-wide (single-source contract), but
    a NEW service's /metrics must report ITS merges, not a predecessor's:
    the count is baselined at construction."""
    first = _tiny_session()
    svc1 = AggregationService(
        first, ServeConfig(quorum=2, deadline_s=5.0),
        traffic=TrafficGenerator(
            TraceConfig(population=first.train_set.num_clients, seed=5)),
    ).start()
    try:
        src = svc1.source()
        first.commit_round(first.dispatch_round(src.next(), LR))
        src.on_committed(first.round)
        assert svc1.metrics_snapshot()["latency_ms"]["count"] >= 2
    finally:
        svc1.close()
    second = _tiny_session()
    svc2 = AggregationService(
        second, ServeConfig(quorum=2, deadline_s=5.0),
        traffic=TrafficGenerator(
            TraceConfig(population=second.train_set.num_clients, seed=5)),
    ).start()
    try:
        assert svc2.metrics_snapshot()["latency_ms"]["count"] == 0
    finally:
        svc2.close()


def test_instant_signal_safe_skips_jsonl_sink(tmp_path):
    """The SIGTERM handler's instant must land in the in-memory buffer but
    never the JSONL handle (the handler may have interrupted a write on
    that very handle — an interleaved write would tear a line)."""
    path = tmp_path / "ev.jsonl"
    t = obtrace.Tracer()
    t.configure(jsonl_path=str(path))
    t.instant("resilience", "normal")
    t.instant_signal_safe("resilience", "sigterm")
    assert t.event_count() == 2
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert [ev["name"] for ev in lines] == ["normal"]


def test_served_source_on_committed_resolves_latencies():
    """Direct-driver path (bench's shape): record_merges resolves only
    COMMITTED rounds, and served-but-uncommitted rounds drop out on
    stop()."""
    sess = _tiny_session()
    svc = AggregationService(
        sess, ServeConfig(quorum=2, deadline_s=5.0),
        traffic=TrafficGenerator(
            TraceConfig(population=sess.train_set.num_clients, seed=5)),
    ).start()
    before = svc._latency.count
    try:
        src = svc.source()
        prep = src.next()
        assert svc.record_merges() == 0  # nothing committed yet
        sess.commit_round(sess.dispatch_round(prep, LR))
        src.on_committed(sess.round)
        n = svc._latency.count - before
        assert n >= 2  # at least the quorum's submissions merged
        src.next()  # served, never dispatched/committed
        src.stop()
        assert svc.record_merges() == 0  # uncommitted round was discarded
    finally:
        svc.close()


# --------------------------------------------------- registry contracts


def test_registry_kinds_marks_and_percentiles():
    reg = obreg.Registry()
    c = reg.counter("c")
    c.inc()
    c.inc(2)
    assert c.value == 3
    m = reg.mark()
    c.inc(5)
    assert m.delta("c") == 5
    assert m.delta("never_seen") == 0  # born after the mark: full value
    g = reg.gauge("g")
    g.set(2)
    g.set(1)
    assert g.value == 1 and g.max == 2
    h = reg.histogram("h")
    for i in range(100):
        h.observe(i)
    assert h.count == 100
    assert h.percentile(50) == 50
    s = h.summary()
    assert s["p50"] == 50 and s["p99"] == 99 and s["count"] == 100
    assert reg.histogram("h") is h  # get-or-create
    with pytest.raises(TypeError, match="one name, one kind"):
        reg.gauge("c")
    mt = reg.meter("m", window_s=10.0)
    mt.record(5)
    assert mt.rate() == 0.5
    snap = reg.snapshot()
    assert snap["c"] == 8.0 and snap["h"]["p50"] == 50


def test_histogram_window_bounds_memory():
    h = obreg.Histogram("h", window=64)
    for i in range(1000):
        h.observe(float(i))
    assert h.count == 1000  # cumulative count survives the window
    assert h.percentile(0) >= 936  # percentiles over the recent window


def test_registry_thread_safe_under_ingest_path():
    """8 transport threads hammering submit() with the accept hook wired
    to registry metrics (the live serve shape): every accept must count
    exactly once everywhere."""
    reg = obreg.Registry()
    accepted = reg.counter("accepted")
    rate = reg.meter("rate")
    lat = reg.histogram("lat")

    def hook(n):
        accepted.inc(n)
        rate.record(n)
        lat.observe(0.5)

    n_threads, per_thread = 8, 500
    q = IngestQueue(capacity=n_threads * per_thread + 1)
    q.on_accept = hook
    q.open_round(0, list(range(n_threads * per_thread)))

    def worker(k):
        for cid in range(k * per_thread, (k + 1) * per_thread):
            q.submit(Submission(client_id=cid, round=0))

    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    assert q.accepted == total
    assert int(accepted.value) == total
    assert lat.count == total


def test_runstats_is_a_registry_delta_view():
    """run_loop fills RunStats from registry mark deltas — the registry
    counters must advance by exactly what the stats report."""
    reg = obreg.default()
    before_rounds = reg.counter("runner_rounds_total").value
    before_drains = reg.counter("runner_drains_total").value
    s = _tiny_session()
    stats = run_loop(s, FedOptimizer(lambda _: LR, 1),
                     RunnerConfig(total_rounds=3, eval_every=3))
    assert stats.rounds == 3
    assert reg.counter("runner_rounds_total").value - before_rounds == 3
    assert (reg.counter("runner_drains_total").value - before_drains
            == stats.drains >= 1)
    assert stats.evals == 1
    # the phase histograms populated (the serve endpoint reads these)
    for phase in ("prepare", "dispatch", "drain", "commit"):
        assert reg.histogram(f"runner_phase_{phase}_ms").count > 0, phase


# ------------------------------------------------------- profiler window


def test_profile_rounds_spec_validation():
    assert parse_rounds_spec("") is None
    assert parse_rounds_spec("2:5") == (2, 5)
    for bad in ("5", "a:b", "3:1", "-1:2"):
        with pytest.raises(ValueError):
            parse_rounds_spec(bad)
    with pytest.raises(ValueError, match="profile_dir"):
        ProfileWindow(0, 1, "")


def test_profile_window_start_stop_at_round_boundaries(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    pw = ProfileWindow.parse("1:2", str(tmp_path))
    pw.on_dispatch(0)
    assert calls == []  # before the window
    pw.on_dispatch(1)
    assert calls == [("start", str(tmp_path))]
    pw.on_committed(2)  # round 1 committed; round 2 (END) still open
    assert len(calls) == 1
    pw.on_committed(3)  # round 2 committed -> stop
    assert calls[-1] == ("stop",)
    pw.on_dispatch(1)  # window is one-shot
    assert len(calls) == 2


def test_profile_window_block_overlap_and_resume_past(tmp_path, monkeypatch,
                                                      capsys):
    """A fused dispatch block OVERLAPPING the window starts the capture (a
    block cannot be split, so the capture is a round-aligned superset);
    a run that begins PAST the window declares it dead loudly instead of
    silently arming at the wrong rounds."""
    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append("start"))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append("stop"))
    pw = ProfileWindow.parse("5:6", str(tmp_path))
    pw.on_dispatch(0, rounds=4)  # block [0..3]: ends before the window
    assert calls == []
    pw.on_dispatch(4, rounds=4)  # block [4..7] contains round 5 -> start
    assert calls == ["start"]
    pw.on_committed(8)
    assert calls == ["start", "stop"]

    pw2 = ProfileWindow.parse("5:6", str(tmp_path))
    pw2.on_dispatch(8)  # resumed run already past the window
    assert calls == ["start", "stop"]  # no capture armed
    assert "behind the run" in capsys.readouterr().err
    pw2.on_dispatch(5)  # declared dead: stays dead
    assert calls == ["start", "stop"]


def test_profile_window_degrades_to_loud_noop(tmp_path, monkeypatch, capsys):
    def boom(d):
        raise RuntimeError("no profiler on this backend")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    pw = ProfileWindow.parse("0:1", str(tmp_path))
    pw.on_dispatch(0)  # must not raise
    err = capsys.readouterr().err
    assert "degrades to a no-op" in err
    pw.on_committed(5)
    pw.close()  # nothing active: both no-ops


# --------------------------------------------- crash-safe JSONL logging


def test_tablelogger_rows_carry_schema_version(tmp_path, capsys):
    from commefficient_tpu.utils.logging import (
        JSONL_SCHEMA_VERSION, TableLogger,
    )

    path = tmp_path / "rows.jsonl"
    t = TableLogger(str(path))
    t.append({"round": 0, "loss": 1.5})
    t.append({"round": 1, "loss": 1.25})
    t.close()
    rows = [json.loads(line) for line in path.read_text().splitlines()]
    assert [r["schema"] for r in rows] == [JSONL_SCHEMA_VERSION] * 2
    assert rows[1]["round"] == 1
    # the stdout table prints the CALLER's columns (no schema column)
    out = capsys.readouterr().out
    assert "schema" not in out


def test_tablelogger_killed_process_leaves_whole_lines(tmp_path):
    """SIGKILL a process mid-logging: every line already on disk must be a
    complete JSON object (line-buffered single-write append discipline)."""
    path = tmp_path / "rows.jsonl"
    child = (
        "import os, sys\n"
        "sys.stdout = open(os.devnull, 'w')\n"
        "from commefficient_tpu.utils.logging import TableLogger\n"
        f"t = TableLogger({str(path)!r})\n"
        "i = 0\n"
        "while True:\n"
        "    t.append({'round': i, 'loss': i * 0.5, 'pad': 'x' * 256})\n"
        "    i += 1\n"
    )
    p = subprocess.Popen([sys.executable, "-c", child])
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if path.exists() and path.stat().st_size > 8192:
                break
            time.sleep(0.02)
        else:
            pytest.fail("child never wrote enough rows")
    finally:
        p.kill()
        p.wait()
    lines = path.read_text().splitlines()
    assert len(lines) >= 10
    for i, line in enumerate(lines):
        row = json.loads(line)  # a torn line would raise here
        assert row["round"] == i
