"""Async run-loop harness (runner/): the acceptance pin is that the
overlapped loop — background batch prefetch, deferred device_get of
metrics, checkpoint writes on a writer thread — produces BIT-IDENTICAL
final params and logged metrics to `--sync_loop` (the old serial loop),
including across an emergency-checkpoint resume, because both drive the
identical compiled programs in the identical order with the identical host
RNG stream.

Same tiny-MLP + synthetic-CIFAR substitution as tests/test_resilience.py
(the loop logic is model-agnostic; ResNet-9 compiles for minutes on this
1-core box)."""

import json
import os
import threading
import time

import numpy as np
import pytest

import jax

import cv_train
from commefficient_tpu.resilience import EXIT_RESUMABLE
from commefficient_tpu.runner import AsyncCheckpointWriter, RoundPrefetcher
from commefficient_tpu.utils import checkpoint as ckpt
from commefficient_tpu.utils.config import make_parser, resolve_defaults

LR = 0.05


def _argv(extra=()):
    return [
        "--dataset", "cifar10", "--mode", "uncompressed", "--num_clients", "8",
        "--num_workers", "2", "--local_batch_size", "4", "--lr_scale", "0.05",
        "--weight_decay", "0", "--data_root", "/nonexistent", *extra,
    ]


def _args(extra=()):
    return resolve_defaults(make_parser("cv").parse_args(_argv(extra)))


@pytest.fixture()
def tiny_cv(tmp_path, monkeypatch):
    import flax.linen as nn

    import commefficient_tpu.data.cifar as cifar_mod

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=64, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)

    class _TinyNet(nn.Module):
        num_classes: int = 10
        dtype: str = "float32"

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(self.num_classes)(x)

    monkeypatch.setattr(cv_train, "ResNet9", _TinyNet)
    return tmp_path


def _rows(path):
    """Logged JSONL rows minus wall-clock (the one legitimately
    loop-dependent field)."""
    rows = [json.loads(line) for line in open(path)]
    for r in rows:
        r.pop("time_s")
    return rows


def _assert_params_equal(sa, sb):
    for x, y in zip(
        jax.tree.leaves(jax.device_get(sa.state["params"])),
        jax.tree.leaves(jax.device_get(sb.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ------------------------------------------------- the acceptance headline


@pytest.mark.chaos
def test_async_loop_bit_identical_to_sync(tiny_cv, tmp_path):
    """Multi-round run through the REAL CLI, eval cadence mid-run, mixed
    block sizes (--rounds_per_dispatch 2 against --eval_every 3 exercises
    BOTH the fused-block and per-round dispatch paths): the async loop's
    final params and every logged metric row must be bit-identical to
    --sync_loop's."""
    base = _argv(("--num_rounds", "6", "--eval_every", "3",
                  "--rounds_per_dispatch", "2"))
    la, lb = str(tmp_path / "sync.jsonl"), str(tmp_path / "async.jsonl")
    sa = cv_train.main(base + ["--sync_loop", "--log_jsonl", la])
    sb = cv_train.main(base + ["--log_jsonl", lb])
    assert sa.round == sb.round == 6
    _assert_params_equal(sa, sb)
    rows_a, rows_b = _rows(la), _rows(lb)
    assert rows_a and rows_a == rows_b


@pytest.mark.chaos
def test_async_loop_preempt_resume_bit_identical(tiny_cv, tmp_path):
    """SIGTERM mid-block under the async loop (prefetcher ahead, rounds in
    flight, periodic saves on the writer thread): drain -> emergency
    checkpoint -> exit 75; the relaunched --resume run must finish with
    params bit-identical to an uninterrupted --sync_loop run. This is the
    'checkpoint+resume mid-run + SIGTERM mid-block' acceptance case."""
    base = _argv(("--num_rounds", "6"))
    sa = cv_train.main(base + ["--sync_loop"])  # uninterrupted reference

    ckdir = str(tmp_path / "ck")
    chaos = ["--checkpoint_dir", ckdir, "--checkpoint_every", "2",
             "--fault_plan", "preempt@2"]
    with pytest.raises(SystemExit) as ei:
        cv_train.main(base + chaos)
    assert ei.value.code == EXIT_RESUMABLE
    # the SIGTERM fired as round 2 dispatched; the drain let it commit, so
    # the emergency checkpoint is a verified round-3 boundary
    names = sorted(d for d in os.listdir(ckdir) if d.startswith("round_"))
    assert names[-1] == "round_00000003"
    assert ckpt.verify(os.path.join(ckdir, names[-1])) is True

    sc = cv_train.main(base + chaos + ["--resume"])
    assert sc.round == 6
    _assert_params_equal(sa, sc)


@pytest.mark.chaos
def test_prefetcher_deterministic_under_injected_data_fault(tiny_cv):
    """A data load failing transiently ON THE PREFETCH THREAD must recover
    via the retry wrapper's RNG-snapshot restore and still serve the
    bit-identical round sequence — prefetch never perturbs the client
    stream."""
    a, _ = cv_train.build(_args())
    ms_a = [a.run_round(LR) for _ in range(4)]

    b, _ = cv_train.build(_args(("--fault_plan", "data_fail@1:times=2")))
    src = RoundPrefetcher(b, b.round, depth=2)
    try:
        ms_b = [b.commit_round(b.dispatch_round(src.next(), LR))[0]
                for _ in range(4)]
    finally:
        src.stop()
    assert [m["loss_sum"] for m in ms_a] == [m["loss_sum"] for m in ms_b]
    _assert_params_equal(a, b)


@pytest.mark.chaos
def test_async_periodic_checkpoints_land_verified(tiny_cv, tmp_path):
    """Periodic saves ride the writer thread in the async loop; by process
    end every committed checkpoint must verify and include the final
    round's synchronous save."""
    ckdir = str(tmp_path / "ck")
    s = cv_train.main(_argv(("--num_rounds", "6", "--checkpoint_dir", ckdir,
                             "--checkpoint_every", "2")))
    assert s.round == 6
    names = sorted(d for d in os.listdir(ckdir) if d.startswith("round_"))
    assert names and names[-1] == "round_00000006"
    for name in names:
        assert ckpt.verify(os.path.join(ckdir, name)) is True
    # no staging dirs leaked by the overlapped writes
    assert not [d for d in os.listdir(ckdir) if d.startswith(".tmp_round_")]


# ----------------------------------------------------- prefetcher contract


def test_prefetcher_serves_rounds_in_order(tiny_cv):
    """The prefetched sequence must equal inline prepare_round calls on an
    identically-seeded session: same cohorts, same batches, same snapshot
    chain (the double buffer only changes WHEN host work runs)."""
    a, _ = cv_train.build(_args())
    b, _ = cv_train.build(_args())
    inline = [a.prepare_round(i) for i in range(3)]
    src = RoundPrefetcher(b, 0, depth=2)
    try:
        fetched = [src.next() for _ in range(3)]
    finally:
        src.stop()
    for pa, pb in zip(inline, fetched):
        assert pa.rnd == pb.rnd
        np.testing.assert_array_equal(pa.ids, pb.ids)
        for k in pa.batch:
            np.testing.assert_array_equal(pa.batch[k], pb.batch[k])
        np.testing.assert_array_equal(np.asarray(pa.sub), np.asarray(pb.sub))


def test_prefetcher_degrades_exhausted_loader_to_masked_cohort(tiny_cv):
    """Retry exhaustion no longer kills the run (cohort fault tolerance):
    the prepared round comes back fully masked (validity all zero, zero
    batch) with every cohort id re-queued for a later round — on the
    prefetch thread exactly as inline."""
    from commefficient_tpu.federated import engine

    b, _ = cv_train.build(
        _args(("--fault_plan", "data_fail@0:times=99", "--max_retries", "1"))
    )
    src = RoundPrefetcher(b, 0, depth=2)
    try:
        prep = src.next()
    finally:
        src.stop()
    assert prep.masked == b.num_workers
    np.testing.assert_array_equal(
        np.asarray(prep.batch[engine.VALID_KEY]),
        np.zeros(b.num_workers, np.float32))
    assert prep.requeue_depth == b.num_workers
    assert sorted(prep.requeue) == sorted(int(i) for i in prep.ids)
    # the degraded round still runs: fully-dropped-cohort semantics
    m = b.commit_round(b.dispatch_round(prep, 0.05))[0]
    assert m["participants"] == 0.0 and m["clients_dropped"] == b.num_workers


def test_prefetcher_stop_unblocks_producer(tiny_cv):
    """stop() must join a producer blocked on a full queue (the preemption
    exit path cannot afford to leak a thread mid-assembly)."""
    b, _ = cv_train.build(_args())
    src = RoundPrefetcher(b, 0, depth=1)
    src.next()  # ensure the thread is live and refilling
    time.sleep(0.05)  # let it block on the full queue
    src.stop()
    assert not src._pf._thread.is_alive()


# --------------------------------------------------------- writer contract


def test_writer_coalesces_requests():
    gate = threading.Event()
    calls = []

    def save():
        gate.wait(5)
        calls.append(1)
        return f"p{len(calls)}"

    w = AsyncCheckpointWriter(save)
    w.request()
    deadline = time.monotonic() + 5
    while not w._busy and time.monotonic() < deadline:
        time.sleep(0.005)  # wait until the first save is IN flight
    for _ in range(4):
        w.request()  # all four coalesce into ONE follow-up save
    gate.set()
    w.drain()
    w.close()
    # four requests landed while a save was in flight: ONE follow-up save
    # ran (capturing the newest state), all four counted as coalesced
    assert len(calls) == 2
    assert w.saves_completed == 2 and w.saves_coalesced == 4
    assert w.last_path == "p2"


def test_writer_reraises_failure_at_drain():
    def bad():
        raise OSError("disk gone")

    w = AsyncCheckpointWriter(bad, alert=lambda m: None)
    w.request()
    with pytest.raises(OSError, match="disk gone"):
        w.drain()
    w.drain()  # error surfaced once; the writer stays usable
    w.close()


def test_writer_close_finishes_outstanding_work():
    calls = []
    w = AsyncCheckpointWriter(lambda: calls.append(1) or "p")
    w.request()
    w.close()
    assert calls == [1]
    with pytest.raises(RuntimeError, match="closed"):
        w.request()


def test_superseded_inflight_releases_state_batch_commit_exact(tiny_cv):
    """The HBM contract of the async pipeline: once a newer dispatch
    supersedes an in-flight round, its server-state tree is released (only
    the newest is ever published at a batch commit) — and the batch commit
    still produces the exact per-round metrics and final params of the
    synchronous loop."""
    s, _ = cv_train.build(_args())
    i1 = s.dispatch_round(s.prepare_round(0), LR)
    i2 = s.dispatch_round(s.prepare_round(1), LR)
    i1.release_state()
    assert i1.new_state is None  # nothing pins the intermediate tree
    out = s.commit_rounds([i1, i2], jax.device_get([i1.metrics, i2.metrics]))
    assert len(out) == 2 and s.round == 2

    b, _ = cv_train.build(_args())
    mb = [b.run_round(LR) for _ in range(2)]
    assert [m["loss_sum"] for m in out] == [m["loss_sum"] for m in mb]
    _assert_params_equal(s, b)
    # releasing the NEWEST entry is a contract violation, loudly
    i3 = s.dispatch_round(s.prepare_round(2), LR)
    i3.release_state()
    with pytest.raises(RuntimeError, match="release_state"):
        s.commit_rounds([i3], [jax.device_get(i3.metrics)])


def test_async_writer_failure_does_not_block_final_save(tiny_cv, tmp_path):
    """A periodic save failing on the writer thread hours into a run must
    not block the FINAL synchronous save at normal completion — that save
    is the corrective action."""
    from commefficient_tpu.federated.api import FedOptimizer
    from commefficient_tpu.runner import RunnerConfig, run_loop

    # checkpoint_dir arms emergency saves -> donation off -> writer eligible
    s, _ = cv_train.build(_args(("--checkpoint_dir", str(tmp_path / "ck"))))
    calls = []

    def flaky_save():
        calls.append(1)
        if len(calls) == 1:
            raise OSError("transient ENOSPC")
        return "saved"

    stats = run_loop(
        s, FedOptimizer(lambda _: LR, 1),
        RunnerConfig(total_rounds=4, eval_every=4, checkpoint_every=2,
                     checkpoint_dir=str(tmp_path / "ck")),
        save_ckpt=flaky_save,
    )
    assert s.round == 4
    assert stats.async_checkpoints >= 1  # the periodic save rode the writer
    assert len(calls) >= 2  # failed periodic + successful final


def test_session_reusable_after_async_loop(tiny_cv):
    """run_loop's exit path rewinds the live host RNG / device key to the
    committed boundary (the prefetcher prepared — and drew RNG for — rounds
    that were never dispatched), so continuing to drive the session stays on
    the bit-identical sequence the sync loop would produce."""
    from commefficient_tpu.federated.api import FedOptimizer
    from commefficient_tpu.runner import RunnerConfig, run_loop

    a, _ = cv_train.build(_args())
    b, _ = cv_train.build(_args())
    run_loop(a, FedOptimizer(lambda _: LR, 1),
             RunnerConfig(total_rounds=3, eval_every=3))  # async
    run_loop(b, FedOptimizer(lambda _: LR, 1),
             RunnerConfig(total_rounds=3, eval_every=3, sync_loop=True))
    _assert_params_equal(a, b)
    ma, mb = a.run_round(LR), b.run_round(LR)  # continue past the loop
    assert ma["loss_sum"] == mb["loss_sum"]
    _assert_params_equal(a, b)


# ------------------------------------------------------- session invariant


def test_evaluate_refuses_inflight_pipeline(tiny_cv):
    """Eval must only run at a drained boundary (the committed state is the
    only consistent — and, under donation, the only live — view)."""
    s, test_set = cv_train.build(_args())
    prep = s.prepare_round(0)
    infl = s.dispatch_round(prep, LR)
    with pytest.raises(RuntimeError, match="in-flight"):
        s.evaluate(test_set, 32)
    s.commit_round(infl)
    s.evaluate(test_set, 32)  # drained: fine
