"""Multi-host bootstrap tests (parallel/distributed.py). Real multi-process
launches can't run here; what IS testable: the auto-detection contract (a
plain host never touches the distributed runtime), and a forced single-
process initialize in a SUBPROCESS (the distributed service binds for the
life of a process — keep it out of the shared pytest process)."""

import os
import subprocess
import sys

import pytest

from commefficient_tpu.parallel import distributed


def _clear(monkeypatch):
    for v in distributed._COORDINATOR_ENV_VARS + ("TPU_WORKER_HOSTNAMES",):
        monkeypatch.delenv(v, raising=False)


def test_auto_mode_is_noop_without_multihost_env(monkeypatch):
    _clear(monkeypatch)
    assert not distributed.detected()
    assert distributed.initialize() is False  # no env -> no init
    assert distributed._INITIALIZED is False


def test_detection_markers(monkeypatch):
    _clear(monkeypatch)
    # a SINGLE worker hostname (single-host TPU VMs, this machine's tunnel
    # plugin) must NOT read as a cluster
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0")
    assert not distributed.detected()
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")
    assert distributed.detected()
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    assert distributed.detected()


def test_auto_mode_degrades_when_backend_already_up(monkeypatch):
    """The pytest process has live CPU backends; auto mode must warn and
    run single-host, NOT raise (a launcher env var must never kill a job
    that works on one host)."""
    import jax

    jax.devices()  # ensure backends are up
    _clear(monkeypatch)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    assert distributed.initialize() is False
    import pytest as _pytest

    with _pytest.raises(RuntimeError):
        distributed.initialize(force=True)


def test_forced_single_process_initialize_subprocess():
    """force=True with an explicit localhost coordinator: a 1-process
    'cluster' initializes, and the engine's mesh/devices view is unchanged."""
    import socket

    with socket.socket() as sk:  # ephemeral port: concurrent runs can't collide
        sk.bind(("localhost", 0))
        port = sk.getsockname()[1]
    code = f"""
from commefficient_tpu.utils.hermetic import force_hermetic_cpu
force_hermetic_cpu(4)  # >= 4 devices (an inherited XLA_FLAGS count wins)
from commefficient_tpu.parallel import distributed, mesh
ok = distributed.initialize(
    force=True, coordinator_address="localhost:{port}",
    num_processes=1, process_id=0,
)
assert ok and distributed.initialize()  # idempotent
info = distributed.process_info()
assert info['process_index'] == 0 and info['process_count'] == 1
assert info['local_devices'] == info['global_devices'] >= 4
m = mesh.make_mesh(4)
print("OK", info)
"""
    from conftest import hermetic_subprocess_env, repo_root

    env = hermetic_subprocess_env()
    # this test pins its own device count via force_hermetic_cpu inside the
    # child; drop the mesh pin so the two don't fight
    del env["XLA_FLAGS"], env["JAX_PLATFORMS"]
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240, env=env, cwd=repo_root(),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout


_TWO_PROC_CHILD = """
import sys
port, pid_ = sys.argv[1], int(sys.argv[2])
sys.path.insert(0, {repo!r})
sys.path.insert(0, {repo!r} + "/tests")
from commefficient_tpu.utils.hermetic import force_hermetic_cpu
force_hermetic_cpu(4)  # 4 local devices per process -> 8 global
from commefficient_tpu.parallel import distributed, mesh as meshlib
ok = distributed.initialize(force=True,
                            coordinator_address="localhost:" + port,
                            num_processes=2, process_id=pid_)
import jax, jax.numpy as jnp
info = distributed.process_info()
assert ok and info["process_count"] == 2, info
assert info["local_devices"] == 4 and info["global_devices"] == 8, info
from jax.flatten_util import ravel_pytree
from commefficient_tpu.federated import engine
from commefficient_tpu.modes.config import ModeConfig
from test_engine import _data, init_mlp, mlp_loss
mesh = meshlib.make_mesh(8)  # GLOBAL mesh spanning both processes
params = init_mlp(jax.random.PRNGKey(0))
d = ravel_pytree(params)[0].size
cfg = engine.EngineConfig(mode=ModeConfig(
    mode="sketch", d=d, k=16, num_rows=3, num_cols=1024,
    hash_family="rotation", momentum_type="virtual", error_type="virtual"))
state = engine.init_server_state(cfg, params, {{}})
data = _data(jax.random.PRNGKey(5), 64)
batch = jax.tree.map(lambda a: a.reshape((8, 8) + a.shape[1:]), data)
gbatch = meshlib.shard_client_batch(mesh, batch)  # global sharded arrays
step = jax.jit(engine.make_round_step(mlp_loss, cfg))
for i in range(2):
    state, _, metrics = step(state, gbatch, {{}}, jnp.float32(0.1),
                             jax.random.PRNGKey(i))
psum = float(jnp.asarray(ravel_pytree(state["params"])[0]).sum())
print("RESULT", pid_, float(metrics["loss_sum"]), psum, flush=True)
"""


def test_two_process_cluster_round_matches_single_process():
    """VERDICT r3 #8: TWO real processes (4 CPU devices each) form a cluster
    via jax.distributed, build one GLOBAL 8-device mesh, and run two sketch
    rounds SPMD — both processes must agree with each other and with the
    single-process 8-device run (the detection/bootstrap path was previously
    reasoned-but-unobserved for the >= 2 case)."""
    import socket

    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.federated import engine as eng
    from commefficient_tpu.modes.config import ModeConfig
    from commefficient_tpu.parallel import mesh as meshlib

    from conftest import hermetic_subprocess_env, repo_root
    from test_engine import _data, init_mlp, mlp_loss

    with socket.socket() as sk:
        sk.bind(("localhost", 0))
        port = sk.getsockname()[1]
    env = hermetic_subprocess_env()
    # children pin their own 4-device count via force_hermetic_cpu
    del env["XLA_FLAGS"], env["JAX_PLATFORMS"]
    code = _TWO_PROC_CHILD.format(repo=repo_root())
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(port), str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for i in range(2)
    ]
    results = {}
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            assert p.returncode == 0, err[-2000:]
            line = next(ln for ln in out.splitlines() if ln.startswith("RESULT"))
            _, pid_, loss, psum = line.split()
            results[int(pid_)] = (float(loss), float(psum))
    finally:
        # one child dying leaves its peer blocked in the coordinator join —
        # never leak it into the rest of the pytest run
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    assert results[0] == results[1]  # SPMD: both controllers see one program

    # single-process 8-device reference (this pytest process's CPU mesh)
    mesh = meshlib.make_mesh(8)
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    cfg = eng.EngineConfig(mode=ModeConfig(
        mode="sketch", d=d, k=16, num_rows=3, num_cols=1024,
        hash_family="rotation", momentum_type="virtual", error_type="virtual"))
    state = eng.init_server_state(cfg, params, {})
    data = _data(jax.random.PRNGKey(5), 64)
    batch = jax.tree.map(lambda a: a.reshape((8, 8) + a.shape[1:]), data)
    gbatch = meshlib.shard_client_batch(mesh, batch)
    step = jax.jit(eng.make_round_step(mlp_loss, cfg))
    for i in range(2):
        state, _, metrics = step(state, gbatch, {}, jnp.float32(0.1),
                                 jax.random.PRNGKey(i))
    ref_loss = float(metrics["loss_sum"])
    ref_psum = float(jnp.asarray(ravel_pytree(state["params"])[0]).sum())
    got_loss, got_psum = results[0]
    assert got_loss == pytest.approx(ref_loss, rel=1e-5)
    assert got_psum == pytest.approx(ref_psum, rel=1e-4)


def test_initialize_from_args_forces_on_explicit_cluster_flags(monkeypatch):
    """Explicit --coordinator_address without --multihost must still attempt
    the cluster join (and, with backends already up in this process, raise
    rather than silently train single-host on every node)."""
    import argparse

    import jax
    import pytest as _pytest

    jax.devices()
    _clear(monkeypatch)
    args = argparse.Namespace(multihost=False, coordinator_address="h0:1",
                              num_processes=2, process_id=0)
    with _pytest.raises(RuntimeError):
        distributed.initialize_from_args(args)
    plain = argparse.Namespace(multihost=False, coordinator_address=None,
                               num_processes=None, process_id=None)
    assert distributed.initialize_from_args(plain) is False
