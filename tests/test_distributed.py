"""Multi-host bootstrap tests (parallel/distributed.py). Real multi-process
launches can't run here; what IS testable: the auto-detection contract (a
plain host never touches the distributed runtime), and a forced single-
process initialize in a SUBPROCESS (the distributed service binds for the
life of a process — keep it out of the shared pytest process)."""

import os
import subprocess
import sys

from commefficient_tpu.parallel import distributed


def test_auto_mode_is_noop_without_multihost_env(monkeypatch):
    for v in distributed._MULTIHOST_ENV_VARS:
        monkeypatch.delenv(v, raising=False)
    assert not distributed.detected()
    assert distributed.initialize() is False  # no env -> no init
    assert distributed._INITIALIZED is False


def test_detection_markers(monkeypatch):
    for v in distributed._MULTIHOST_ENV_VARS:
        monkeypatch.delenv(v, raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")
    assert distributed.detected()
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    assert distributed.detected()


def test_forced_single_process_initialize_subprocess():
    """force=True with an explicit localhost coordinator: a 1-process
    'cluster' initializes, and the engine's mesh/devices view is unchanged."""
    import socket

    with socket.socket() as sk:  # ephemeral port: concurrent runs can't collide
        sk.bind(("localhost", 0))
        port = sk.getsockname()[1]
    code = f"""
from commefficient_tpu.utils.hermetic import force_hermetic_cpu
force_hermetic_cpu(4)  # >= 4 devices (an inherited XLA_FLAGS count wins)
from commefficient_tpu.parallel import distributed, mesh
ok = distributed.initialize(
    force=True, coordinator_address="localhost:{port}",
    num_processes=1, process_id=0,
)
assert ok and distributed.initialize()  # idempotent
info = distributed.process_info()
assert info['process_index'] == 0 and info['process_count'] == 1
assert info['local_devices'] == info['global_devices'] >= 4
m = mesh.make_mesh(4)
print("OK", info)
"""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240, env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout
