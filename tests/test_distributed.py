"""Multi-host bootstrap tests (parallel/distributed.py). Real multi-process
launches can't run here; what IS testable: the auto-detection contract (a
plain host never touches the distributed runtime), and a forced single-
process initialize in a SUBPROCESS (the distributed service binds for the
life of a process — keep it out of the shared pytest process)."""

import os
import subprocess
import sys

from commefficient_tpu.parallel import distributed


def _clear(monkeypatch):
    for v in distributed._COORDINATOR_ENV_VARS + ("TPU_WORKER_HOSTNAMES",):
        monkeypatch.delenv(v, raising=False)


def test_auto_mode_is_noop_without_multihost_env(monkeypatch):
    _clear(monkeypatch)
    assert not distributed.detected()
    assert distributed.initialize() is False  # no env -> no init
    assert distributed._INITIALIZED is False


def test_detection_markers(monkeypatch):
    _clear(monkeypatch)
    # a SINGLE worker hostname (single-host TPU VMs, this machine's tunnel
    # plugin) must NOT read as a cluster
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0")
    assert not distributed.detected()
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")
    assert distributed.detected()
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    assert distributed.detected()


def test_auto_mode_degrades_when_backend_already_up(monkeypatch):
    """The pytest process has live CPU backends; auto mode must warn and
    run single-host, NOT raise (a launcher env var must never kill a job
    that works on one host)."""
    import jax

    jax.devices()  # ensure backends are up
    _clear(monkeypatch)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")
    assert distributed.initialize() is False
    import pytest as _pytest

    with _pytest.raises(RuntimeError):
        distributed.initialize(force=True)


def test_forced_single_process_initialize_subprocess():
    """force=True with an explicit localhost coordinator: a 1-process
    'cluster' initializes, and the engine's mesh/devices view is unchanged."""
    import socket

    with socket.socket() as sk:  # ephemeral port: concurrent runs can't collide
        sk.bind(("localhost", 0))
        port = sk.getsockname()[1]
    code = f"""
from commefficient_tpu.utils.hermetic import force_hermetic_cpu
force_hermetic_cpu(4)  # >= 4 devices (an inherited XLA_FLAGS count wins)
from commefficient_tpu.parallel import distributed, mesh
ok = distributed.initialize(
    force=True, coordinator_address="localhost:{port}",
    num_processes=1, process_id=0,
)
assert ok and distributed.initialize()  # idempotent
info = distributed.process_info()
assert info['process_index'] == 0 and info['process_count'] == 1
assert info['local_devices'] == info['global_devices'] >= 4
m = mesh.make_mesh(4)
print("OK", info)
"""
    from conftest import hermetic_subprocess_env, repo_root

    env = hermetic_subprocess_env()
    # this test pins its own device count via force_hermetic_cpu inside the
    # child; drop the mesh pin so the two don't fight
    del env["XLA_FLAGS"], env["JAX_PLATFORMS"]
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=240, env=env, cwd=repo_root(),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "OK" in out.stdout


def test_initialize_from_args_forces_on_explicit_cluster_flags(monkeypatch):
    """Explicit --coordinator_address without --multihost must still attempt
    the cluster join (and, with backends already up in this process, raise
    rather than silently train single-host on every node)."""
    import argparse

    import jax
    import pytest as _pytest

    jax.devices()
    _clear(monkeypatch)
    args = argparse.Namespace(multihost=False, coordinator_address="h0:1",
                              num_processes=2, process_id=0)
    with _pytest.raises(RuntimeError):
        distributed.initialize_from_args(args)
    plain = argparse.Namespace(multihost=False, coordinator_address=None,
                               num_processes=None, process_id=None)
    assert distributed.initialize_from_args(plain) is False
