"""Always-on aggregation (PR 11): pipelined invites, double-buffered merge,
buffered async mode, chunked payload frames.

The acceptance pins live here:

- a PIPELINED served run (--serve_pipeline: the serve cycle on the
  always-on worker) is BIT-identical — params + every logged row + requeue
  state — to the serial served run, announce AND payload paths;
- a buffered-ASYNC run (--serve_async) where every submission answers the
  open round dispatches the plain merge program every round and is
  BIT-identical to the synchronous run (the FedBuff staleness machinery
  costs nothing until someone is actually late);
- the ingest queue holds TWO concurrently-open rounds with per-round
  quarantine-median snapshots (the pipelined-invite admission path);
- tables too big for one frame cross the wire as chunked continuation
  frames, reassembled INSIDE validate_payload (G011) — any partial,
  reordered, duplicated, or damaged sequence is MALFORMED.
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

import cv_train
from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated.api import FederatedSession, FedOptimizer
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.obs import registry as obreg
from commefficient_tpu.obs import trace as obtrace
from commefficient_tpu.resilience import EXIT_RESUMABLE, FaultPlan
from commefficient_tpu.runner.loop import RunnerConfig, run_loop
from commefficient_tpu.serve import (
    AggregationService,
    IngestQueue,
    PayloadPolicy,
    ServeConfig,
    SocketTransport,
    Submission,
    TraceConfig,
    TrafficGenerator,
    submit_over_socket,
    validate_payload,
)
from commefficient_tpu.serve.ingest import (
    ACCEPTED,
    ACCEPTED_STALE,
    DUPLICATE,
    MALFORMED,
    NOT_INVITED,
    OUT_OF_ROUND,
    QUARANTINED,
)
from commefficient_tpu.sketch.payload import encode_frame

LR = 0.05


# ------------------------------------------------------------------ fixtures


def _quad_loss(params, net_state, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
    mask = batch["mask"]
    count = jnp.maximum(mask.sum(), 1.0)
    per_ex = (err ** 2).sum(-1)
    return (per_ex * mask).sum() / count, {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()}}


def _tiny_session(payload=False, stale_slots=0, seed=0, workers=4):
    rs = np.random.RandomState(0)
    x = rs.randn(96, 6).astype(np.float32)
    w_true = rs.randn(6, 3).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int32)
    train = FedDataset(x, y, shard_iid(len(x), 12, np.random.RandomState(1)))
    params = {"w": jnp.asarray(rs.randn(6, 3).astype(np.float32) * 0.1),
              "b": jnp.zeros(3)}
    d = ravel_pytree(params)[0].size
    if payload:
        mc = ModeConfig(mode="sketch", d=d, k=4, num_rows=3, num_cols=16,
                        momentum_type="virtual", error_type="virtual")
    else:
        mc = ModeConfig(mode="uncompressed", d=d, momentum=0.9,
                        momentum_type="virtual", error_type="none")
    return FederatedSession(
        train_loss_fn=_quad_loss, eval_loss_fn=_quad_loss,
        params=params, net_state={}, mode_cfg=mc, train_set=train,
        num_workers=workers, local_batch_size=4, seed=seed,
        wire_payloads=payload, stale_slots=stale_slots,
    )


def _serve(session, cfg, rounds, trace_seed=5):
    """Drive `rounds` served rounds through the REAL runner dispatch shape
    (next -> dispatch -> on_dispatched -> commit -> on_committed); returns
    the metric rows."""
    svc = AggregationService(
        session, cfg,
        traffic=TrafficGenerator(
            TraceConfig(population=session.train_set.num_clients,
                        seed=trace_seed))).start()
    rows = []
    try:
        src = svc.source()
        for _ in range(rounds):
            prep = src.next()
            rows.append(session.commit_round(
                session.dispatch_round(prep, LR))[0])
            src.on_dispatched(session.round - 1)
            src.on_committed(session.round)
        src.stop()
        # the run_loop exit discipline: the worker may have prepared
        # rounds that never committed — rewind the live streams to the
        # committed boundary exactly like the runner's finally does
        import collections

        with session.mutate_lock:
            rng_state, rng_key = session.rng_snapshot
            session.rng.set_state(rng_state)
            session._rng_key = rng_key
            session._requeue = collections.deque(
                session._requeue_committed)
            session._requeue_enqueued = dict(
                session._requeue_ages_committed)
    finally:
        svc.close()
    return rows


def _assert_params_equal(sa, sb):
    for x, y in zip(
        jax.tree.leaves(jax.device_get(sa.state["params"])),
        jax.tree.leaves(jax.device_get(sb.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _assert_rows_equal(ra, rb):
    for a, b in zip(ra, rb):
        assert set(a) == set(b)
        for k in a:
            assert a[k] == b[k], (k, a[k], b[k])


# -------------------------------------------------- two concurrently-open rounds


def _sub(cid, rnd=0, latency=0.1, payload=None):
    return Submission(client_id=cid, round=rnd, latency_s=latency,
                      payload=payload)


def test_two_open_rounds_route_independently():
    """The pipelined-invite admission path: rounds r and r+1 both open,
    submissions route to THEIR window, NOT_INVITED/DUPLICATE are
    per-round, and closing r leaves r+1 collecting."""
    q = IngestQueue(capacity=8)
    q.open_round(0, [1, 2])
    q.open_round(1, [2, 3])
    assert q.open_rounds() == [0, 1]
    assert q.submit(_sub(1, rnd=0)) == ACCEPTED
    assert q.submit(_sub(3, rnd=1)) == ACCEPTED
    assert q.submit(_sub(3, rnd=0)) == NOT_INVITED  # per-round invites
    assert q.submit(_sub(2, rnd=1)) == ACCEPTED
    assert q.submit(_sub(2, rnd=1)) == DUPLICATE    # per-round dedup
    arr0 = q.close_round(0)
    assert [a.client_id for a in arr0] == [1]
    assert q.open_rounds() == [1]
    assert q.submit(_sub(9, rnd=0)) == OUT_OF_ROUND  # 0 closed
    assert [a.client_id for a in q.arrivals(1)] == [3, 2]


def test_third_concurrent_round_refused():
    q = IngestQueue(capacity=8, max_open_rounds=2)
    q.open_round(0, [1])
    q.open_round(1, [2])
    with pytest.raises(RuntimeError, match="max_open_rounds"):
        q.open_round(2, [3])
    q.close_round(0)
    q.open_round(2, [3])  # a slot freed: fine


def test_two_open_rounds_payload_medians_are_per_round():
    """An early payload push for the OPEN round r+1 validates against
    r+1's quarantine-median snapshot, never r's — the 'right state' half
    of the pipelined-invite contract."""
    medians = iter([1.0, 100.0])
    policy = PayloadPolicy(rows=1, cols=4, clip_multiple=2.0,
                           quarantine_median=lambda: next(medians))
    q = IngestQueue(capacity=8, payload_policy=policy)
    q.open_round(0, [1, 2])    # snapshots median 1.0
    q.open_round(1, [1, 2])    # snapshots median 100.0
    big = np.full((1, 4), 50.0, np.float32)  # L2 = 100 > 2*1, < 2*100
    assert q.submit(_sub(1, rnd=0, payload=big)) == QUARANTINED
    assert q.submit(_sub(1, rnd=1, payload=big)) == ACCEPTED
    arr = q.arrivals(1)
    assert len(arr) == 1 and arr[0].table is not None


def test_stale_band_admits_late_payload_against_its_rounds_state():
    """The buffered-async band: a late payload for a recently-closed round
    is ACCEPTED_STALE (validated against ITS round's retained median and
    invite list); beyond the band it bounces; dup/uninvited still mean
    what they meant."""
    medians = iter([1000.0, 1000.0, 1000.0])
    policy = PayloadPolicy(rows=1, cols=4, clip_multiple=2.0,
                           quarantine_median=lambda: next(medians))
    q = IngestQueue(capacity=8, payload_policy=policy, stale_rounds=1,
                    stale_capacity=4)
    t = np.ones((1, 4), np.float32)
    q.open_round(0, [1, 2, 3])
    assert q.submit(_sub(1, rnd=0, payload=t)) == ACCEPTED
    q.close_round(0)
    q.open_round(1, [4])
    # late for round 0: inside the 1-round band
    assert q.submit(_sub(2, rnd=0, payload=t)) == ACCEPTED_STALE
    assert q.submit(_sub(2, rnd=0, payload=t)) == DUPLICATE
    assert q.submit(_sub(1, rnd=0, payload=t)) == DUPLICATE  # already in
    assert q.submit(_sub(9, rnd=0, payload=t)) == NOT_INVITED
    stale = q.drain_stale()
    assert [(s.round, s.client_id) for s in stale] == [(0, 2)]
    assert q.counters()["accepted_stale"] == 1
    # the band moves with the newest window: round 0 ages out at open(2)
    q.close_round(1)
    q.open_round(2, [5])
    assert q.submit(_sub(3, rnd=0, payload=t)) == OUT_OF_ROUND


# ------------------------------------------------------------- chunked frames


def _policy(rows=3, cols=128):
    return PayloadPolicy(rows=rows, cols=cols)


def test_chunked_frame_reassembles_bit_exact():
    rs = np.random.RandomState(3)
    table = rs.randn(3, 128).astype(np.float32)
    frames = encode_frame(table, max_frame_bytes=1024)
    assert isinstance(frames, list) and len(frames) >= 2
    assert [f["seq"] for f in frames] == list(range(len(frames)))
    got, decision, detail = validate_payload(frames, _policy())
    assert decision == ACCEPTED, detail
    np.testing.assert_array_equal(got, table)
    # a table under the cap stays a single frame, same bytes decoded
    single = encode_frame(table)
    got1, decision1, _ = validate_payload(single, _policy())
    assert decision1 == ACCEPTED
    np.testing.assert_array_equal(got1, table)


@pytest.mark.parametrize("damage", [
    "drop_middle", "drop_last", "reorder", "duplicate", "flip_bit",
    "mixed_schema", "head_only",
])
def test_chunk_sequence_damage_is_malformed(damage):
    """Any broken chunk sequence — partial, reordered, duplicated,
    bit-flipped, schema-mixed — is MALFORMED: reassembly lives inside the
    G011 boundary and never guesses."""
    rs = np.random.RandomState(4)
    table = rs.randn(3, 128).astype(np.float32)
    frames = encode_frame(table, max_frame_bytes=1024)
    assert len(frames) >= 3
    if damage == "drop_middle":
        frames = [frames[0]] + frames[2:]
    elif damage == "drop_last":
        frames = frames[:-1]
    elif damage == "reorder":
        frames = [frames[1], frames[0]] + frames[2:]
    elif damage == "duplicate":
        frames = frames + [frames[-1]]
    elif damage == "flip_bit":
        frames[1] = dict(frames[1])
        frames[1]["data"] = FaultPlan.corrupt_frame(
            {"data": frames[1]["data"]})["data"]
    elif damage == "mixed_schema":
        frames[1] = dict(frames[1])
        frames[1]["schema"] = 99
    elif damage == "head_only":
        frames = [frames[0]]
    _, decision, _ = validate_payload(frames, _policy())
    assert decision == MALFORMED


@pytest.mark.parametrize("cap", [1000, 1002, 1003, 1024, 1100])
def test_chunked_frames_reassemble_at_any_frame_cap(cap):
    """The chunk raw-byte budget is floored to a base64 group (multiple of
    3): a cap whose derived budget is NOT a multiple of 3 must not leave
    '=' padding mid-stream and reject legitimate chunked submissions
    (regression: caps like 1002/1003 used to MALFORMED every table)."""
    rs = np.random.RandomState(7)
    table = rs.randn(3, 128).astype(np.float32)
    frames = encode_frame(table, max_frame_bytes=cap)
    assert isinstance(frames, list) and len(frames) >= 2
    got, decision, detail = validate_payload(frames, _policy())
    assert decision == ACCEPTED, (cap, detail)
    np.testing.assert_array_equal(got, table)


def test_lone_mid_sequence_frame_is_malformed():
    """A single frame claiming seq>0/total>1 (its siblings never arrived)
    must not pass the single-frame path."""
    rs = np.random.RandomState(5)
    frames = encode_frame(rs.randn(3, 128).astype(np.float32),
                          max_frame_bytes=1024)
    _, decision, detail = validate_payload(frames[1], _policy())
    assert decision == MALFORMED and "chunk" in detail or "partial" in detail


def test_chunked_frames_over_real_socket():
    """A table bigger than the transport's frame cap round-trips the
    loopback socket as continuation lines and admits bit-exact; a
    connection that dies mid-sequence admits nothing and counts
    MALFORMED."""
    rs = np.random.RandomState(6)
    table = rs.randn(3, 128).astype(np.float32)  # 1536 B > 1024 cap
    q = IngestQueue(capacity=8, payload_policy=_policy())
    q.open_round(0, [7, 8])
    t = SocketTransport(q, max_frame_bytes=1024, read_deadline_s=5.0)
    t.start()
    try:
        status = submit_over_socket(t.address, _sub(7, payload=table),
                                    max_frame_bytes=1024)
        assert status == ACCEPTED
        arr = q.arrivals(0)
        assert len(arr) == 1
        np.testing.assert_array_equal(arr[0].table, table)
        # partial sequence: send only the first chunk line, then die
        import json as _json
        import socket as _socket

        from commefficient_tpu.serve.transport import _wire_lines

        lines = _wire_lines(_sub(8, payload=table), 1024)
        assert len(lines) >= 2 and "chunk" in lines[0]
        before = q.counters()["rejected_malformed"]
        with _socket.create_connection(t.address, timeout=5) as s:
            s.sendall(_json.dumps(lines[0]).encode() + b"\n")
        # the handler sees EOF with the sequence open
        deadline = 50
        while (q.counters()["rejected_malformed"] == before
               and deadline > 0):
            import time as _time

            _time.sleep(0.05)
            deadline -= 1
        assert q.counters()["rejected_malformed"] == before + 1
        assert [a.client_id for a in q.arrivals(0)] == [7]
    finally:
        t.stop()


def test_chunk_sequence_byte_flood_cut_off_before_completion():
    """A hostile sequence claiming a huge total must be cut off once it
    buffers more bytes than the expected payload could encode to — BEFORE
    completion, so per-connection memory never waits on a complete
    submission."""
    q = IngestQueue(capacity=8, payload_policy=_policy())  # 1536-byte table
    q.open_round(0, [7])
    t = SocketTransport(q, max_frame_bytes=2048, read_deadline_s=5.0)
    seqs: dict = {}
    # the junk field shows the budget counts WIRE bytes, not just data —
    # padding any other frame field must not evade the cut
    reply = None
    for i in range(64):  # way past one table's encoded size
        reply = t._handle_chunk(
            {"client_id": 7, "round": 0,
             "chunk": {"schema": 2, "seq": i, "total": 64,
                       "junk": "A" * 1500, "data": ""}},
            seqs, 1600)
        if reply is not None:
            break
    assert reply is not None and reply["status"] == MALFORMED
    assert "exceeds" in reply["detail"]
    assert not seqs  # the sequence was discarded, not retained


def test_rewind_prunes_uncommitted_stale_entries_from_queue():
    """A stale arrival for a round the runner never committed must not
    survive rewind_to_committed — the round is re-served, and its
    pre-rewind stale twin would otherwise double-merge the client."""
    medians = iter([1000.0, 1000.0])
    policy = PayloadPolicy(rows=1, cols=4, clip_multiple=2.0,
                           quarantine_median=lambda: next(medians))
    q = IngestQueue(capacity=8, payload_policy=policy, stale_rounds=2,
                    stale_capacity=4)
    t = np.ones((1, 4), np.float32)
    q.open_round(5, [1, 2])
    q.close_round(5)
    q.open_round(6, [3])
    assert q.submit(_sub(1, rnd=5, payload=t)) == ACCEPTED_STALE
    # rounds >= 5 never committed: the entry (and round 5's retained band
    # state) must unwind; a later push for round 5 is OUT_OF_ROUND until
    # it is re-served
    dropped = q.prune_stale(5)
    assert dropped == 1
    assert q.drain_stale() == []
    assert q.submit(_sub(2, rnd=5, payload=t)) == OUT_OF_ROUND


def test_prune_stale_rewinds_early_push_high_water_mark():
    """After a rewind, the replayed timeline's BUFFERED/OUT_OF_ROUND
    verdicts must match the original run's round for round — the
    early-push high-water mark rewinds with the windows."""
    from commefficient_tpu.serve.ingest import BUFFERED

    q = IngestQueue(capacity=8)
    q.open_round(0, [1])
    q.open_round(1, [2])
    q.close_round(0)
    q.close_round(1)
    q.prune_stale(1)  # rounds >= 1 never committed: replay from round 1
    q.open_round(1, [2])
    # a push for round 2 is EARLY again, exactly like the original run
    # (without the high-water rewind it would bounce OUT_OF_ROUND)
    assert q.submit(_sub(5, rnd=2)) == BUFFERED


def test_shed_retry_of_stale_admitted_submission_hears_duplicate():
    """At-least-once under overload, stale band included: a retry of a
    submission already ACCEPTED_STALE must hear DUPLICATE, not SHEDDING."""
    policy = PayloadPolicy(rows=1, cols=4)
    q = IngestQueue(capacity=4, pending_capacity=0, payload_policy=policy,
                    stale_rounds=1, stale_capacity=4, shed_watermark=0.25)
    t = np.ones((1, 4), np.float32)
    q.open_round(0, [1, 2, 3])
    q.close_round(0)
    q.open_round(1, [4, 5, 6])
    assert q.submit(_sub(1, rnd=0, payload=t)) == ACCEPTED_STALE
    # push depth past the shed watermark
    assert q.submit(_sub(4, rnd=1, payload=t)) == ACCEPTED
    assert q.submit(_sub(5, rnd=1, payload=t)) in (ACCEPTED, "SHEDDING")
    assert q.depth() >= q._shed_depth
    # the lost-reply retry: already in the stale band == success
    assert q.submit(_sub(1, rnd=0, payload=t)) == DUPLICATE


# ------------------------------------------------ THE pipelined parity pins


def test_pipelined_announce_bitwise_equal_serial():
    """Pipelined announce serving == serial announce serving, bitwise:
    params, every logged row, and the requeue state — the worker is the
    same single producer, just earlier."""
    a = _tiny_session()
    ra = _serve(a, ServeConfig(quorum=2, deadline_s=1.0), 4)
    b = _tiny_session()
    rb = _serve(b, ServeConfig(quorum=2, deadline_s=1.0, pipeline=True), 4)
    _assert_rows_equal(ra, rb)
    _assert_params_equal(a, b)
    assert list(a._requeue) == list(b._requeue)
    assert a._requeue_enqueued == b._requeue_enqueued


def test_pipelined_payload_bitwise_equal_serial():
    """Pipelined wire-payload serving == serial, bitwise — the dispatch
    gate hands the worker the exact head state the serial source read."""
    a = _tiny_session(payload=True)
    ra = _serve(a, ServeConfig(quorum=2, deadline_s=1.0,
                               payload="sketch"), 4)
    b = _tiny_session(payload=True)
    rb = _serve(b, ServeConfig(quorum=2, deadline_s=1.0, payload="sketch",
                               pipeline=True), 4)
    _assert_rows_equal(ra, rb)
    _assert_params_equal(a, b)


def test_pipelined_runner_loop_bitwise_equal_serial_and_idle_measured():
    """Through the REAL async runner: pipelined == serial bitwise, and the
    loop measured the commit-to-dispatch gap (server_idle_ms present)."""
    def run(pipelined):
        s = _tiny_session(payload=True)
        svc = AggregationService(
            s, ServeConfig(quorum=2, deadline_s=1.0, payload="sketch",
                           pipeline=pipelined),
            traffic=TrafficGenerator(
                TraceConfig(population=12, seed=5))).start()
        try:
            stats = run_loop(
                s, FedOptimizer(lambda e: LR, 3),
                RunnerConfig(total_rounds=5, eval_every=100),
                source=svc.source())
        finally:
            svc.close()
        return s, stats

    sa, stats_a = run(False)
    sb, stats_b = run(True)
    _assert_params_equal(sa, sb)
    assert stats_b.rounds == stats_a.rounds == 5
    assert stats_b.server_idle_ms >= 0.0
    assert stats_b.server_idle_ms_max >= stats_b.server_idle_ms


def test_pipelined_session_reuse_rewinds_to_committed():
    """A pipelined loop stopped mid-stream (worker rounds prepared but
    never committed) rewinds; a SECOND loop on the same session+service
    continues bit-identically with an uninterrupted serial run."""
    a = _tiny_session()
    svc = AggregationService(
        a, ServeConfig(quorum=2, deadline_s=1.0, pipeline=True),
        traffic=TrafficGenerator(TraceConfig(population=12, seed=5))).start()
    try:
        opt = FedOptimizer(lambda e: LR, 3)
        run_loop(a, opt, RunnerConfig(total_rounds=2, eval_every=100),
                 source=svc.source())
        run_loop(a, opt, RunnerConfig(total_rounds=5, eval_every=100),
                 source=svc.source())
    finally:
        svc.close()
    b = _tiny_session()
    _serve(b, ServeConfig(quorum=2, deadline_s=1.0), 5)
    _assert_params_equal(a, b)
    assert a.round == b.round == 5


def test_pipeline_stage_spans_and_histograms_emitted(tmp_path):
    """The double-buffered pipeline is observable: serve-pipeline stage
    spans land in the trace, the stage histograms fill, and the worker's
    serve_round spans carry the round numbers."""
    tracer = obtrace.get()
    tracer.configure(trace_path=str(tmp_path / "trace.json"))
    try:
        base = {
            st: obreg.default().histogram(f"serve_stage_{st}_ms").count
            for st in obreg.SERVE_STAGES}
        a = _tiny_session(payload=True)
        _serve(a, ServeConfig(quorum=2, deadline_s=1.0, payload="sketch",
                              pipeline=True), 3)
        events = tracer.events()
        pipe = [e for e in events if e.get("cat") == "serve-pipeline"]
        names = {e.get("name") for e in pipe}
        assert "serve_round" in names
        for st in obreg.SERVE_STAGES:
            assert f"stage:{st}" in names, names
            assert (obreg.default().histogram(
                f"serve_stage_{st}_ms").count > base[st]), st
        rounds = {e.get("args", {}).get("round") for e in pipe
                  if e.get("name") == "serve_round"}
        assert {0, 1, 2} <= rounds
    finally:
        tracer.configure()


# --------------------------------------------------- THE async parity pin


def test_async_everyone_on_time_bitwise_equal_sync():
    """Buffered async with the trigger at the full quorum and everyone on
    time NEVER folds stale — every round dispatches the plain merge
    program, and the run is bit-identical to the synchronous one (params +
    every logged row)."""
    a = _tiny_session(payload=True)
    ra = _serve(a, ServeConfig(quorum=4, deadline_s=1e9,
                               payload="sketch"), 4)
    b = _tiny_session(payload=True, stale_slots=4)
    rb = _serve(b, ServeConfig(quorum=4, deadline_s=1e9, payload="sketch",
                               async_mode=True, buffer_size=4), 4)
    _assert_rows_equal(ra, rb)
    _assert_params_equal(a, b)


def test_async_pipelined_straggler_folds_staleness_weighted():
    """The FedBuff behavior: with the buffer trigger below the arrival
    count, stragglers' validated tables fold into the NEXT merge
    (stale_folded metric + counters fire, params stay finite) instead of
    being discarded — and the folded run genuinely differs from the
    drop-the-stragglers sync run."""
    reg = obreg.default()
    base_folded = reg.counter("serve_stale_folded_total").value
    a = _tiny_session(payload=True, stale_slots=4)
    ra = _serve(a, ServeConfig(quorum=4, deadline_s=60.0, payload="sketch",
                               async_mode=True, buffer_size=2,
                               pipeline=True), 5)
    folded = reg.counter("serve_stale_folded_total").value - base_folded
    assert folded > 0
    assert any(r.get("stale_folded", 0) > 0 for r in ra)
    assert any(r.get("stale_weight", 0) > 0 for r in ra)
    # weights are (1+lag)^-0.5 <= 2^-0.5 < 1: the fold is down-weighted
    for r in ra:
        if r.get("stale_folded", 0):
            assert r["stale_weight"] < r["stale_folded"]
    flat = np.asarray(ravel_pytree(jax.device_get(a.state["params"]))[0])
    assert np.isfinite(flat).all()
    # vs sync at the same trigger (stragglers dropped): params differ —
    # the stale mass really entered the table
    b = _tiny_session(payload=True)
    _serve(b, ServeConfig(quorum=2, deadline_s=60.0, payload="sketch"), 5)
    fb = np.asarray(ravel_pytree(jax.device_get(b.state["params"]))[0])
    assert not np.array_equal(flat, fb)


def test_async_stale_band_expiry_drops_and_counts():
    """An entry older than the stale_rounds band is dropped (counted),
    never folded — staleness has a horizon."""
    reg = obreg.default()
    base = reg.counter("serve_stale_dropped_total").value
    a = _tiny_session(payload=True, stale_slots=4)
    svc = AggregationService(
        a, ServeConfig(quorum=4, deadline_s=60.0, payload="sketch",
                       async_mode=True, buffer_size=2, stale_rounds=1),
        traffic=TrafficGenerator(TraceConfig(population=12, seed=5))).start()
    try:
        src = svc.source()
        prep = src.next()
        # age the stash artificially: pretend the stash entries came from
        # far behind the band
        with svc._meta_lock:
            svc._stale_stash = [(e[0] - 5, e[1], e[2], e[3])
                                for e in svc._stale_stash]
        a.commit_round(a.dispatch_round(prep, LR))
        src.on_dispatched(a.round - 1)
        src.next()  # builds round 1's fold: the aged entries drop
        src.stop()
    finally:
        svc.close()
    assert reg.counter("serve_stale_dropped_total").value > base


# ------------------------------------------------------------- config guards


def test_async_config_validation():
    with pytest.raises(ValueError, match="announce"):
        AggregationService(
            _tiny_session(),
            ServeConfig(quorum=2, async_mode=True),
            traffic=TrafficGenerator(TraceConfig()))
    with pytest.raises(ValueError, match="stale_slots"):
        AggregationService(
            _tiny_session(payload=True),
            ServeConfig(quorum=2, payload="sketch", async_mode=True),
            traffic=TrafficGenerator(TraceConfig()))
    with pytest.raises(ValueError, match="serve_buffer"):
        AggregationService(
            _tiny_session(),
            ServeConfig(quorum=2, buffer_size=3),
            traffic=TrafficGenerator(TraceConfig()))


def test_engine_rejects_stale_slots_without_wire_and_composes_robust():
    from commefficient_tpu.federated import engine

    mc = ModeConfig(mode="sketch", d=16, k=4, num_rows=2, num_cols=8,
                    momentum_type="virtual", error_type="virtual")
    with pytest.raises(ValueError, match="wire"):
        engine.EngineConfig(mode=mc, stale_slots=4)
    # async x robust COMPOSES since the per-buffer robust merge landed:
    # stale slots join the weighted order statistics instead of folding
    # linearly (tests/test_async_robust.py pins the semantics)
    cfg = engine.EngineConfig(mode=mc, stale_slots=4, wire_payloads=True,
                              merge_policy="median")
    assert engine.robust_policy(cfg) == "median"


def test_cli_flag_validation():
    from commefficient_tpu.utils.config import make_parser, resolve_defaults

    base = ["--dataset", "cifar10", "--mode", "sketch", "--k", "4"]
    with pytest.raises(SystemExit, match="serve_payload|sketch"):
        resolve_defaults(make_parser("cv").parse_args(
            base + ["--serve", "inproc", "--serve_async"]))
    with pytest.raises(SystemExit, match="serve_async"):
        resolve_defaults(make_parser("cv").parse_args(
            base + ["--serve", "inproc", "--serve_buffer", "3"]))
    with pytest.raises(SystemExit, match="serve"):
        resolve_defaults(make_parser("cv").parse_args(
            base + ["--serve_pipeline"]))


# --------------------------------------------------------------- CLI chaos


@pytest.fixture()
def tiny_cv(tmp_path, monkeypatch):
    import flax.linen as nn

    import commefficient_tpu.data.cifar as cifar_mod

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=64, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)

    class _TinyNet(nn.Module):
        num_classes: int = 10
        dtype: str = "float32"

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(self.num_classes)(x)

    monkeypatch.setattr(cv_train, "ResNet9", _TinyNet)
    return tmp_path


@pytest.mark.chaos
def test_cli_pipelined_preempt_resume_bit_identical(tiny_cv, tmp_path):
    """--serve_pipeline through the real CLI, preempted mid-run: the
    resumed run is bit-identical to the uninterrupted pipelined run —
    prepared-but-uncommitted worker rounds unwind through the existing
    committed-snapshot rewinds."""
    flags = ("--serve", "inproc", "--serve_pipeline", "--serve_quorum", "5",
             "--serve_deadline", "2.0", "--num_rounds", "4")
    argv = [
        "--dataset", "cifar10", "--mode", "uncompressed", "--num_clients",
        "8", "--num_workers", "2", "--local_batch_size", "4", "--lr_scale",
        "0.05", "--weight_decay", "0", "--data_root", "/nonexistent", *flags,
    ]
    before = {t.name for t in threading.enumerate()}
    sa = cv_train.main(list(argv))  # uninterrupted pipelined reference

    ckdir = str(tmp_path / "ck")
    chaos = ["--checkpoint_dir", ckdir, "--checkpoint_every", "2",
             "--fault_plan", "preempt@2"]
    with pytest.raises(SystemExit) as ei:
        cv_train.main(list(argv) + chaos)
    assert ei.value.code == EXIT_RESUMABLE
    sc = cv_train.main(list(argv) + chaos + ["--resume"])
    assert sc.round == 4
    _assert_params_equal(sa, sc)
    assert list(sa._requeue) == list(sc._requeue)
    # and the pipelined CLI run == the serial CLI run, end to end
    sb = cv_train.main([a for a in argv if a != "--serve_pipeline"])
    _assert_params_equal(sa, sb)
    leaked = {t.name for t in threading.enumerate()} - before
    assert not {n for n in leaked if n.startswith("serve-")}, leaked
