"""GPT-2 model + TP layout tests."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from commefficient_tpu.models.gpt2 import TINY, GPT2LMHead
from commefficient_tpu.models.losses import make_lm_loss
from commefficient_tpu.parallel import mesh as meshlib, tp


def test_forward_shapes_and_determinism():
    model = GPT2LMHead(TINY)
    ids = jnp.array(np.random.RandomState(0).randint(0, TINY.vocab_size, (2, 16)))
    params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    out = model.apply({"params": params}, ids, train=False)
    assert out.shape == (2, 16, TINY.vocab_size)
    out2 = model.apply({"params": params}, ids, train=False)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_causality():
    """Changing a future token must not change past logits."""
    model = GPT2LMHead(TINY)
    rng = np.random.RandomState(1)
    ids = jnp.array(rng.randint(0, TINY.vocab_size, (1, 16)))
    params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    out1 = model.apply({"params": params}, ids, train=False)
    ids2 = ids.at[0, 10].set((int(ids[0, 10]) + 1) % TINY.vocab_size)
    out2 = model.apply({"params": params}, ids2, train=False)
    np.testing.assert_allclose(
        np.asarray(out1[0, :10]), np.asarray(out2[0, :10]), rtol=1e-5, atol=1e-5
    )
    assert not np.allclose(np.asarray(out1[0, 10:]), np.asarray(out2[0, 10:]))


def test_lm_loss_masking():
    model = GPT2LMHead(TINY)
    ids = jnp.ones((1, 8), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    loss_fn = make_lm_loss(model, train=False)
    batch_all_masked = {"input_ids": ids, "labels": jnp.full((1, 8), -100, jnp.int32)}
    loss, aux = loss_fn(params, {}, batch_all_masked, None)
    assert float(aux["metrics"]["count"]) == 0.0
    batch = {"input_ids": ids, "labels": ids}
    loss, aux = loss_fn(params, {}, batch, None)
    assert float(aux["metrics"]["count"]) == 7.0  # T-1 shifted positions
    assert np.isfinite(float(loss))


def test_tp_specs_and_sharded_forward():
    model = GPT2LMHead(dataclasses.replace(TINY, n_head=4))
    ids = jnp.zeros((2, 16), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    specs = tp.gpt2_partition_specs(params)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    as_str = {"/".join(getattr(p, "key", str(p)) for p in path): s for path, s in flat}
    assert as_str["h_0/attn/c_attn/kernel"] == P(None, "model")
    assert as_str["h_0/attn/c_proj/kernel"] == P("model", None)
    assert as_str["h_0/mlp/c_fc/kernel"] == P(None, "model")
    assert as_str["wte"] == P()
    assert as_str["h_0/ln_1/scale"] == P()

    ref = model.apply({"params": params}, ids, train=False)
    mesh = meshlib.make_mesh(8, model_parallel=4)
    sharded = tp.shard_params(mesh, params)
    out = jax.jit(lambda p, i: model.apply({"params": p}, i, train=False))(sharded, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_tp_sharded_sketch_federated_round_matches_unsharded():
    """The flagship compression (mode=sketch, FetchSGD algebra) composed with
    Megatron-style tensor parallelism on a (clients, model) mesh: the round
    must equal the unsharded round — the sketch of the raveled TP-sharded
    grads is the same math, GSPMD just places it."""
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.federated import engine
    from commefficient_tpu.models.losses import make_lm_loss
    from commefficient_tpu.modes.config import ModeConfig

    cfg_m = dataclasses.replace(TINY, n_positions=16, dropout=0.0)
    model = GPT2LMHead(cfg_m)
    ids0 = jnp.zeros((1, 16), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids0, train=False)["params"]
    d = ravel_pytree(params)[0].size
    mode_cfg = ModeConfig(
        mode="sketch", d=d, k=64, num_rows=3, num_cols=4096,
        hash_family="rotation", momentum_type="virtual", error_type="virtual",
    )
    cfg = engine.EngineConfig(mode=mode_cfg, weight_decay=1e-4)
    loss_fn = make_lm_loss(model, train=True)
    W = 4
    ids = jax.random.randint(jax.random.PRNGKey(1), (W, 2, 16), 0,
                             cfg_m.vocab_size, jnp.int32)
    batch = {"input_ids": ids, "labels": ids}
    lr = jnp.float32(0.1)

    def run(shard):
        p = jax.tree.map(jnp.copy, params)
        if shard:
            mesh = meshlib.make_mesh(8, model_parallel=2)  # clients=4 x model=2
            p = tp.shard_params(mesh, p)
        state = engine.init_server_state(cfg, p, {})
        step = jax.jit(engine.make_round_step(loss_fn, cfg))
        b = batch
        if shard:
            b = jax.device_put(
                b, jax.sharding.NamedSharding(
                    mesh, P(meshlib.CLIENT_AXIS)))
        for i in range(2):
            state, _, metrics = step(state, b, {}, lr, jax.random.PRNGKey(i))
        return ravel_pytree(state["params"])[0], metrics

    ref, mref = run(False)
    got, mgot = run(True)
    np.testing.assert_allclose(float(mgot["loss_sum"]), float(mref["loss_sum"]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
