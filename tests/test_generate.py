"""Generation / F1-eval tests (SURVEY.md §2 "NLP training CLI": the
reference lineage's sampling+word-F1 eval half; PPL covered in test_gpt2).
The scan decoder is pinned against a plain python-loop decode of the same
model, eos/overflow bookkeeping against a rigged stub model, and the CLI
integration against a tiny end-to-end run."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from commefficient_tpu.models.generate import (
    decode_reply, make_generate, word_f1,
)
from commefficient_tpu.models.gpt2 import TINY, GPT2LMHead


def test_scan_decode_matches_python_loop():
    cfg = dataclasses.replace(TINY, n_positions=32, dropout=0.0)
    model = GPT2LMHead(cfg)
    T, B, max_new = 32, 3, 6
    key = jax.random.PRNGKey(0)
    params = model.init(key, jnp.zeros((1, T), jnp.int32), train=False)["params"]
    pad = 0
    prompt_len = np.array([5, 9, 12], np.int32)
    rng = np.random.RandomState(3)
    ids = np.full((B, T), pad, np.int32)
    types = np.full((B, T), pad, np.int32)
    for b in range(B):
        ids[b, : prompt_len[b]] = rng.randint(1, cfg.vocab_size, prompt_len[b])
        types[b, : prompt_len[b]] = 7

    gen = make_generate(
        model, eos_id=-1, pad_id=pad, reply_type_id=9, max_new=max_new,
        temperature=0.0,
    )  # eos_id=-1: no token matches, so decode runs all max_new steps
    out, lengths = gen(
        params, jnp.asarray(ids), jnp.asarray(types), jnp.asarray(prompt_len),
        jax.random.PRNGKey(1),
    )
    out = np.asarray(out)

    # reference: python loop, full forward each step, argmax at cur-1
    ref = ids.copy()
    rtypes = types.copy()
    for b in range(B):
        cur = int(prompt_len[b])
        for _ in range(max_new):
            logits = model.apply(
                {"params": params}, jnp.asarray(ref), train=False,
                token_type_ids=jnp.asarray(rtypes),
            )
            ref[b, cur] = int(jnp.argmax(logits[b, cur - 1]))
            rtypes[b, cur] = 9
            cur += 1
    np.testing.assert_array_equal(out, ref)
    np.testing.assert_array_equal(np.asarray(lengths), prompt_len + max_new)


class _StubModel:
    """Emits a fixed per-row script of tokens regardless of input: logits at
    position p put all mass on script[row, p+1 - prompt]. Enough to test the
    eos / overflow bookkeeping without a trained model."""

    def __init__(self, script, prompt_len, vocab):
        self.script = script  # [B, S] tokens to emit in order
        self.prompt = prompt_len
        self.vocab = vocab

    def apply(self, variables, ids, train, token_type_ids=None):
        B, T = ids.shape
        logits = np.zeros((B, T, self.vocab), np.float32)
        for b in range(B):
            for p in range(T):
                step = p + 1 - self.prompt[b]  # token to emit AT position p+1
                tok = self.script[b][step] if 0 <= step < len(self.script[b]) else 1
                logits[b, p, tok] = 10.0
        return jnp.asarray(logits)


def test_eos_stops_row_and_length_excludes_eos():
    eos, pad, V = 5, 0, 8
    prompt_len = np.array([3, 3], np.int32)
    # row 0 emits 2 tokens then eos; row 1 never emits eos
    stub = _StubModel([[2, 3, eos, 4, 4], [4, 4, 4, 4, 4]], prompt_len, V)
    gen = make_generate(
        stub, eos_id=eos, pad_id=pad, reply_type_id=7, max_new=5, temperature=0.0,
        last_logit_only=False,
    )
    ids = np.zeros((2, 12), np.int32)
    ids[:, :3] = 2
    out, lengths = gen(
        None, jnp.asarray(ids), jnp.asarray(ids), jnp.asarray(prompt_len),
        jax.random.PRNGKey(0),
    )
    out, lengths = np.asarray(out), np.asarray(lengths)
    assert lengths.tolist() == [5, 8]  # row 0: 3 + 2 (eos excluded); row 1: 3 + 5
    assert out[0, 3:6].tolist() == [2, 3, eos]
    assert out[0, 6:].tolist() == [pad] * 6  # nothing written after eos
    assert out[1, 3:8].tolist() == [4] * 5
    assert decode_reply(
        type("T", (), {"decode": staticmethod(lambda ids: ",".join(map(str, ids)))}),
        out[0], 3, int(lengths[0]),
    ) == "2,3"


def test_overflow_clamps_at_buffer_end():
    eos, pad, V = 5, 0, 8
    prompt_len = np.array([6], np.int32)
    stub = _StubModel([[3] * 10], prompt_len, V)
    gen = make_generate(
        stub, eos_id=eos, pad_id=pad, reply_type_id=7, max_new=10, temperature=0.0,
        last_logit_only=False,
    )
    ids = np.zeros((1, 8), np.int32)
    ids[:, :6] = 2
    out, lengths = gen(
        None, jnp.asarray(ids), jnp.asarray(ids), jnp.asarray(prompt_len),
        jax.random.PRNGKey(0),
    )
    assert int(lengths[0]) == 8  # stopped at the buffer edge, no wraparound
    assert np.asarray(out)[0, 6:].tolist() == [3, 3]


def test_nucleus_sampling_stays_in_nucleus():
    """With a peaked distribution and small top_p, sampling must always pick
    the mode; with top_p=1 it must occasionally pick something else."""
    eos, pad, V = 5, 0, 16
    prompt_len = np.array([2], np.int32)

    class Peaked:
        def apply(self, variables, ids, train, token_type_ids=None):
            B, T = ids.shape
            base = jnp.tile(jnp.linspace(0.0, 2.0, V), (B, T, 1))
            return base.at[..., 9].set(6.0)  # mode = 9, holds > 0.9 mass

    gen_tight = make_generate(
        Peaked(), eos_id=eos, pad_id=pad, reply_type_id=7, max_new=4,
        temperature=1.0, top_p=0.5, last_logit_only=False,
    )
    ids = np.zeros((1, 10), np.int32)
    ids[:, :2] = 1
    out, _ = gen_tight(
        None, jnp.asarray(ids), jnp.asarray(ids), jnp.asarray(prompt_len),
        jax.random.PRNGKey(0),
    )
    assert np.asarray(out)[0, 2:6].tolist() == [9, 9, 9, 9]

    gen_loose = make_generate(
        Peaked(), eos_id=eos, pad_id=pad, reply_type_id=7, max_new=4,
        temperature=3.0, top_p=1.0, last_logit_only=False,
    )
    picks = set()
    for s in range(8):
        out, _ = gen_loose(
            None, jnp.asarray(ids), jnp.asarray(ids), jnp.asarray(prompt_len),
            jax.random.PRNGKey(s),
        )
        picks.update(np.asarray(out)[0, 2:6].tolist())
    assert len(picks) > 1


def test_word_f1():
    assert word_f1("the cat runs", "the cat runs") == 1.0
    assert word_f1("dog", "cat") == 0.0
    assert word_f1("", "") == 1.0
    assert word_f1("", "cat") == 0.0
    # normalization: case + punctuation
    assert word_f1("The CAT, runs!", "the cat runs") == 1.0
    # partial: pred {a b}, gold {a c} -> P=R=1/2, F1=1/2
    assert abs(word_f1("a b", "a c") - 0.5) < 1e-9
    # multiset semantics: repeated words only count to their gold multiplicity
    assert abs(word_f1("a a", "a b") - 0.5) < 1e-9


def test_decode_examples_prompt_and_gold_align():
    from commefficient_tpu.data.personachat import load_personachat_fed

    _, valid, tok = load_personachat_fed(num_clients=20, seq_len=64, seed=0)
    ids, types, labels = valid.decode_examples(4)
    assert ids.shape == types.shape == labels.shape
    for row_ids, row_lab in zip(ids, labels):
        m = row_lab != -100
        assert m.any()
        p0 = int(np.argmax(m))
        # the packed buffer carries the gold reply at the labelled positions
        np.testing.assert_array_equal(row_ids[m], row_lab[m])
        # prompt ends with the reply speaker token
        assert row_ids[p0 - 1] == tok.speaker2_id


def test_gpt2_train_eval_f1_end_to_end(tmp_path):
    import gpt2_train

    log = tmp_path / "log.jsonl"
    gpt2_train.main([
        "--model_size", "tiny", "--mode", "uncompressed", "--num_clients", "16",
        "--num_workers", "4", "--num_rounds", "2", "--eval_every", "2",
        "--seq_len", "48", "--local_batch_size", "2", "--eval_batch_size", "8",
        "--eval_f1", "3", "--decode_max_new", "4", "--log_jsonl", str(log),
    ])
    import json

    rows = [json.loads(ln) for ln in log.read_text().splitlines()]
    assert rows and "val_f1" in rows[-1]
    assert 0.0 <= rows[-1]["val_f1"] <= 1.0


def test_last_logit_fast_path_matches_full_logits():
    """GPT2LMHead.logit_positions (decode fast path: [B, V] head einsum at
    one position) must produce the same decode as the full [B, T, V] path."""
    cfg = dataclasses.replace(TINY, n_positions=24, dropout=0.0)
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 24), jnp.int32), train=False
    )["params"]
    prompt_len = np.array([4, 7], np.int32)
    rng = np.random.RandomState(1)
    ids = np.zeros((2, 24), np.int32)
    types = np.zeros((2, 24), np.int32)
    for b in range(2):
        ids[b, : prompt_len[b]] = rng.randint(1, cfg.vocab_size, prompt_len[b])
    kw = dict(eos_id=-1, pad_id=0, reply_type_id=9, max_new=5, temperature=0.0)
    fast = make_generate(model, last_logit_only=True, **kw)
    slow = make_generate(model, last_logit_only=False, **kw)
    a = fast(params, jnp.asarray(ids), jnp.asarray(types),
             jnp.asarray(prompt_len), jax.random.PRNGKey(0))
    b = slow(params, jnp.asarray(ids), jnp.asarray(types),
             jnp.asarray(prompt_len), jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))
