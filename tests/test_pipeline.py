"""Pipeline-parallel op tests: GPipe-style stage execution over a 'pipe'
mesh axis must match running the same layer stack sequentially, forward and
backward (autodiff through the ppermute schedule)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from commefficient_tpu.ops import pipeline


def _layer_fn(p, h):
    # residual MLP block: shape-preserving, nonlinear, uses both params
    return h + jnp.tanh(h @ p["w"] + p["b"])


def _stacked_layers(key, L, d):
    ks = jax.random.split(key, L)
    return {
        "w": jnp.stack([0.1 * jax.random.normal(k, (d, d)) for k in ks]),
        "b": jnp.zeros((L, d)),
    }


def _sequential(params, x):
    def body(h, p):
        return _layer_fn(p, h), None

    y, _ = jax.lax.scan(body, x, params)
    return y


def _mesh(S):
    return Mesh(np.array(jax.devices()[:S]), ("pipe",))


def test_pipeline_matches_sequential_forward():
    L, d, M, mb = 8, 16, 6, 4
    params = _stacked_layers(jax.random.PRNGKey(0), L, d)
    x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, d))
    want = jax.vmap(lambda m: _sequential(params, m))(x)
    for S in (2, 4, 8):
        mesh = _mesh(S)
        staged = pipeline.stack_stages(params, S)
        got = pipeline.pipeline_apply(
            pipeline.scan_stage(_layer_fn), staged, x, mesh=mesh
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)


def test_pipeline_matches_sequential_backward():
    L, d, M, mb = 4, 8, 5, 2
    params = _stacked_layers(jax.random.PRNGKey(2), L, d)
    x = jax.random.normal(jax.random.PRNGKey(3), (M, mb, d))
    mesh = _mesh(4)
    staged = pipeline.stack_stages(params, 4)

    def loss_pp(p, x):
        y = pipeline.pipeline_apply(pipeline.scan_stage(_layer_fn), p, x, mesh=mesh)
        return jnp.mean(y**2)

    def loss_seq(p, x):
        y = jax.vmap(lambda m: _sequential(p, m))(x)
        return jnp.mean(y**2)

    val_pp, g_pp = jax.jit(jax.value_and_grad(loss_pp))(staged, x)
    val_sq, g_sq = jax.jit(jax.value_and_grad(loss_seq))(params, x)
    np.testing.assert_allclose(float(val_pp), float(val_sq), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_sq)):
        np.testing.assert_allclose(
            np.asarray(a).reshape(np.asarray(b).shape), np.asarray(b),
            rtol=1e-5, atol=1e-6,
        )


def test_pipeline_single_microbatch_and_uneven():
    """M=1 (pure fill/drain) and M not a multiple of S still match."""
    L, d, mb = 4, 8, 3
    params = _stacked_layers(jax.random.PRNGKey(4), L, d)
    mesh = _mesh(4)
    staged = pipeline.stack_stages(params, 4)
    for M in (1, 3, 7):
        x = jax.random.normal(jax.random.PRNGKey(M), (M, mb, d))
        want = jax.vmap(lambda m: _sequential(params, m))(x)
        got = pipeline.pipeline_apply(
            pipeline.scan_stage(_layer_fn), staged, x, mesh=mesh
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-6)


def test_pipeline_gpt2_blocks_match_sequential():
    """The GPipe schedule over REAL GPT-2 transformer blocks (attention +
    MLP + layer norms) matches applying the same blocks sequentially —
    pipeline parallelism is usable for the actual model family, not just
    toy layers."""
    import dataclasses

    from commefficient_tpu.models.gpt2 import TINY, Block

    cfg = dataclasses.replace(TINY, n_positions=16, dropout=0.0)
    L, S, M, mb, T = 4, 4, 3, 2, 16
    block = Block(cfg)
    x0 = jnp.zeros((mb, T, cfg.n_embd))
    keys = jax.random.split(jax.random.PRNGKey(0), L)
    layer_params = jax.vmap(
        lambda k: block.init(k, x0, False)["params"]
    )(keys)  # stacked [L, ...] leaves

    def layer_fn(p, h):
        return block.apply({"params": p}, h, False)

    x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, T, cfg.n_embd))

    def seq(p, m):
        def body(h, lp):
            return layer_fn(lp, h), None

        return jax.lax.scan(body, m, p)[0]

    want = jax.vmap(lambda m: seq(layer_params, m))(x)
    mesh = _mesh(S)
    staged = pipeline.stack_stages(layer_params, S)
    got = pipeline.pipeline_apply(
        pipeline.scan_stage(layer_fn), staged, x, mesh=mesh
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)

    # backward too: grads through the pipelined transformer stack
    def loss_pp(p):
        y = pipeline.pipeline_apply(pipeline.scan_stage(layer_fn), p, x, mesh=mesh)
        return jnp.mean(y**2)

    def loss_seq(p):
        return jnp.mean(jax.vmap(lambda m: seq(p, m))(x) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(staged)
    g_sq = jax.jit(jax.grad(loss_seq))(layer_params)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_sq)):
        np.testing.assert_allclose(
            np.asarray(a).reshape(np.asarray(b).shape), np.asarray(b),
            rtol=2e-4, atol=2e-5,
        )
