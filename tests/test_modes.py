"""Mode-transform tests (SURVEY.md §4): tiny vectors with hand-computed
answers; error-feedback invariant (sent + residual == accumulated)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.modes import modes


def _cfg(**kw):
    base = dict(mode="uncompressed", d=8, momentum_type="none", error_type="none")
    base.update(kw)
    return ModeConfig(**base)


def test_config_rejects_unimplemented_combos():
    with pytest.raises(ValueError):
        _cfg(mode="sketch", k=2, num_cols=4, momentum_type="local", error_type="virtual")
    with pytest.raises(ValueError):
        _cfg(mode="uncompressed", error_type="virtual")
    with pytest.raises(ValueError):
        _cfg(mode="true_topk", k=2, error_type="local")
    with pytest.raises(ValueError):
        _cfg(mode="bogus")
    # sum aggregation of weight deltas has no lr knob to absorb the factor W
    with pytest.raises(ValueError):
        _cfg(mode="fedavg", agg_op="sum")
    with pytest.raises(ValueError):
        _cfg(agg_op="bogus")


def test_uncompressed_is_sgd_with_momentum():
    cfg = _cfg(momentum_type="virtual", momentum=0.5)
    sstate = modes.init_server_state(cfg)
    g = jnp.arange(8, dtype=jnp.float32)
    wire, _ = modes.client_compress(cfg, g, {})
    agg = modes.aggregate(cfg, {"dense": wire["dense"][None, :]})
    d1, sstate = modes.server_step(cfg, agg, sstate, jnp.float32(0.1))
    np.testing.assert_allclose(np.asarray(d1), 0.1 * np.arange(8), rtol=1e-6)
    d2, sstate = modes.server_step(cfg, agg, sstate, jnp.float32(0.1))
    # V = 0.5*g + g = 1.5g -> delta = 0.15g
    np.testing.assert_allclose(np.asarray(d2), 0.15 * np.arange(8), rtol=1e-6)


def test_true_topk_hand_computed():
    cfg = _cfg(mode="true_topk", k=2, momentum_type="none", error_type="virtual")
    sstate = modes.init_server_state(cfg)
    g = jnp.array([0.1, -5.0, 0.2, 3.0, 0.0, 0.0, 0.0, 0.0])
    agg = {"dense": g}
    delta, sstate = modes.server_step(cfg, agg, sstate, jnp.float32(1.0))
    expect = np.zeros(8, np.float32)
    expect[1], expect[3] = -5.0, 3.0
    np.testing.assert_allclose(np.asarray(delta), expect, rtol=1e-6)
    # error keeps the untransmitted mass
    np.testing.assert_allclose(
        np.asarray(sstate["Verror"]), [0.1, 0, 0.2, 0, 0, 0, 0, 0], rtol=1e-6
    )
    # next round: error feedback promotes 0.2 then 0.1
    delta2, sstate = modes.server_step(
        cfg, {"dense": jnp.zeros(8)}, sstate, jnp.float32(1.0)
    )
    got = np.asarray(delta2)
    assert got[2] == pytest.approx(0.2) and got[0] == pytest.approx(0.1)
    np.testing.assert_allclose(np.asarray(sstate["Verror"]), np.zeros(8), atol=1e-7)


def test_true_topk_error_feedback_invariant():
    """sent + residual == accumulated (lr-scaled), over random rounds."""
    cfg = _cfg(mode="true_topk", k=3, d=32, momentum_type="none", error_type="virtual")
    sstate = modes.init_server_state(cfg)
    rng = np.random.RandomState(0)
    lr = 0.5
    total_sent = np.zeros(32, np.float32)
    total_grad = np.zeros(32, np.float32)
    for _ in range(10):
        g = rng.normal(size=32).astype(np.float32)
        total_grad += lr * g
        delta, sstate = modes.server_step(cfg, {"dense": jnp.asarray(g)}, sstate, jnp.float32(lr))
        total_sent += np.asarray(delta)
    np.testing.assert_allclose(total_sent + np.asarray(sstate["Verror"]), total_grad, rtol=1e-4, atol=1e-5)


def test_local_topk_error_feedback():
    cfg = _cfg(mode="local_topk", k=1, d=4, momentum_type="none", error_type="local", num_clients=2)
    cstate = modes.empty_client_row(cfg)
    g = jnp.array([1.0, -3.0, 0.5, 0.0])
    wire, cstate = modes.client_compress(cfg, g, cstate)
    assert int(wire["idx"][0]) == 1 and float(wire["vals"][0]) == -3.0
    np.testing.assert_allclose(np.asarray(cstate["error"]), [1.0, 0.0, 0.5, 0.0], rtol=1e-6)
    # residual promotes idx 0 next round
    wire2, cstate = modes.client_compress(cfg, jnp.zeros(4), cstate)
    assert int(wire2["idx"][0]) == 0
    np.testing.assert_allclose(np.asarray(cstate["error"]), [0.0, 0.0, 0.5, 0.0], rtol=1e-6)


def test_sketch_mode_roundtrip():
    """sketch mode recovers a heavy gradient coordinate and maintains the
    FetchSGD error-feedback algebra (residual at sent coords ≈ 0)."""
    d = 512
    cfg = _cfg(mode="sketch", d=d, k=4, num_rows=5, num_cols=256,
               momentum_type="none", error_type="virtual")
    sstate = modes.init_server_state(cfg)
    g = np.random.RandomState(0).normal(0, 0.01, d).astype(np.float32)
    g[[7, 100, 300, 444]] = [4.0, -6.0, 5.0, -3.0]
    wires = []
    for _ in range(3):  # 3 identical clients
        w, _ = modes.client_compress(cfg, jnp.asarray(g), {})
        wires.append(w["table"])
    agg = modes.aggregate(cfg, {"table": jnp.stack(wires)})
    delta, sstate = modes.server_step(cfg, agg, sstate, jnp.float32(1.0))
    got = np.asarray(delta)
    nz = np.nonzero(got)[0]
    assert set(nz.tolist()) == {7, 100, 300, 444}
    np.testing.assert_allclose(got[nz], g[nz], rtol=0.1, atol=0.2)


def test_sketch_linearity_client_mean_equals_per_client():
    """is_linear contract: compressing the client-mean equals averaging
    per-client sketches."""
    d = 128
    cfg = _cfg(mode="sketch", d=d, k=4, num_rows=3, num_cols=64,
               momentum_type="none", error_type="virtual")
    rng = np.random.RandomState(1)
    gs = jnp.asarray(rng.normal(size=(5, d)).astype(np.float32))
    per_client = jnp.stack([modes.client_compress(cfg, g, {})[0]["table"] for g in gs])
    agg1 = modes.aggregate(cfg, {"table": per_client})["table"]
    agg2 = modes.client_compress(cfg, gs.mean(0), {})[0]["table"]
    np.testing.assert_allclose(np.asarray(agg1), np.asarray(agg2), rtol=1e-4, atol=1e-5)
    assert modes.is_linear(cfg)
    assert not modes.is_linear(_cfg(mode="local_topk", k=1, d=4, momentum_type="none",
                                    error_type="local", num_clients=2))


def test_local_topk_virtual_error_feedback_invariant():
    """error_type=virtual: ONE server-side residual on the aggregated sparse
    update (no [num_clients, d] state). sent + residual == accumulated."""
    cfg = _cfg(mode="local_topk", k=2, d=16, momentum_type="none", error_type="virtual")
    assert not cfg.needs_local_state  # the whole point of virtual error
    sstate = modes.init_server_state(cfg)
    rng = np.random.RandomState(3)
    lr = 0.5
    total_sent = np.zeros(16, np.float32)
    total_agg = np.zeros(16, np.float32)
    for _ in range(8):
        gs = rng.normal(size=(3, 16)).astype(np.float32)  # 3 clients
        wires = [modes.client_compress(cfg, jnp.asarray(g), {})[0] for g in gs]
        agg = modes.aggregate(cfg, {
            "idx": jnp.stack([w["idx"] for w in wires]),
            "vals": jnp.stack([w["vals"] for w in wires]),
        })
        total_agg += lr * np.asarray(agg["dense"])
        delta, sstate = modes.server_step(cfg, agg, sstate, jnp.float32(lr))
        total_sent += np.asarray(delta)
        assert np.count_nonzero(np.asarray(delta)) <= cfg.k
    np.testing.assert_allclose(
        total_sent + np.asarray(sstate["Verror"]), total_agg, rtol=1e-4, atol=1e-5
    )


def test_sum_vs_mean_lr_translation():
    """agg_op="sum" at lr η is bit-for-bit agg_op="mean" at lr η·W (ModeConfig
    docs): server steps are positively homogeneous, so the documented lr
    translation for reference (FetchSGD Alg. 1) hyperparameters is exact."""
    W, lr = 4, 0.25
    rng = np.random.RandomState(7)
    for mode_kw in (
        dict(mode="uncompressed", d=32, momentum_type="virtual", momentum=0.9,
             error_type="none"),
        dict(mode="true_topk", d=32, k=3, momentum_type="virtual", error_type="virtual"),
        dict(mode="local_topk", d=32, k=3, momentum_type="none", error_type="virtual"),
        dict(mode="sketch", d=64, k=4, num_rows=3, num_cols=32,
             momentum_type="virtual", error_type="virtual"),
    ):
        cfg_mean = _cfg(**mode_kw, agg_op="mean")
        cfg_sum = _cfg(**mode_kw, agg_op="sum")
        st_mean = modes.init_server_state(cfg_mean)
        st_sum = modes.init_server_state(cfg_sum)
        for _ in range(5):
            gs = rng.normal(size=(W, mode_kw["d"])).astype(np.float32)
            wires = [modes.client_compress(cfg_mean, jnp.asarray(g), {})[0] for g in gs]
            stacked = {k: jnp.stack([w[k] for w in wires]) for k in wires[0]}
            d_mean, st_mean = modes.server_step(
                cfg_mean, modes.aggregate(cfg_mean, stacked), st_mean, jnp.float32(lr * W)
            )
            d_sum, st_sum = modes.server_step(
                cfg_sum, modes.aggregate(cfg_sum, stacked), st_sum, jnp.float32(lr)
            )
            np.testing.assert_allclose(
                np.asarray(d_mean), np.asarray(d_sum), rtol=1e-5, atol=1e-6,
                err_msg=f"mode={mode_kw['mode']}"
            )


def test_fedavg_server_average():
    cfg = _cfg(mode="fedavg", d=4, momentum_type="none", num_local_iters=2)
    sstate = modes.init_server_state(cfg)
    deltas = jnp.array([[1.0, 0, 0, 0], [3.0, 0, 0, 0]])  # two clients
    agg = modes.aggregate(cfg, {"dense": deltas})
    delta, _ = modes.server_step(cfg, agg, sstate, jnp.float32(1.0))
    np.testing.assert_allclose(np.asarray(delta), [2.0, 0, 0, 0], rtol=1e-6)


def test_topk_impl_approx_recall():
    """approx top-k must recover (nearly all of) the exact top-k; on CPU the
    approx lowering is exact, so assert the contract rather than exact
    equality to stay meaningful on TPU too."""
    v = jax.random.normal(jax.random.PRNGKey(0), (100_000,))
    k = 1000
    ei, _ = modes.topk_dense(v, k)
    ai, avals = modes.topk_dense(v, k, impl="approx")
    recall = len(set(np.asarray(ai).tolist()) & set(np.asarray(ei).tolist())) / k
    # recall_target=0.95 bounds the EXPECTED recall; leave slack so the
    # assert holds on TPU (where approx is really approximate), not just on
    # CPU's exact fallback
    assert recall >= 0.9
    np.testing.assert_array_equal(np.asarray(avals), np.asarray(v)[np.asarray(ai)])


def test_topk_impl_approx_unsketch():
    """Sketch-mode unsketch with impl=approx recovers planted heavy hitters
    through both the chunked path (num_slabs > 1) and matches the engine's
    flag plumbing."""
    from commefficient_tpu.sketch import csvec

    spec = csvec.CSVecSpec(d=20_000, c=2048, r=5, family="rotation", seed=9)
    v = 0.01 * jax.random.normal(jax.random.PRNGKey(1), (spec.d,))
    hot = jnp.arange(0, spec.d, spec.d // 50)[:40]
    v = v.at[hot].set(5.0)
    t = csvec.sketch_vec(spec, v)
    idx, vals = csvec.unsketch_topk(spec, t, 40, impl="approx")
    hot_set = set(np.asarray(hot).tolist())
    got = len(hot_set & set(np.asarray(idx).tolist())) / len(hot_set)
    assert got >= 0.9  # ~0.95 expected recall on TPU; exact on CPU

    cfg = ModeConfig(mode="sketch", d=spec.d, k=40, num_rows=5, num_cols=2048,
                     hash_family="rotation", momentum_type="virtual",
                     error_type="virtual", topk_impl="approx", seed=spec.seed)
    delta, _ = modes.server_step(
        cfg, {"table": t[None].mean(0)}, modes.init_server_state(cfg),
        jnp.float32(1.0),
    )
    nz = set(np.flatnonzero(np.asarray(delta)).tolist())
    assert len(hot_set & nz) / len(hot_set) >= 0.9


def test_topk_impl_validation():
    with pytest.raises(ValueError):
        ModeConfig(mode="true_topk", d=100, k=5, momentum_type="none",
                   error_type="none", topk_impl="bogus")


@pytest.mark.parametrize("mode_kw", [
    dict(mode="sketch", k=4, num_rows=3, num_cols=64, d=256,
         momentum_type="virtual", error_type="virtual"),
    dict(mode="true_topk", k=4, d=256, momentum_type="virtual",
         error_type="virtual"),
    dict(mode="true_topk", k=4, d=256, momentum_type="virtual",
         error_type="none"),
    dict(mode="local_topk", k=4, d=256, momentum_type="none",
         error_type="virtual"),
    dict(mode="local_topk", k=4, d=256, momentum_type="none",
         error_type="local"),
    dict(mode="fedavg", d=256, num_local_iters=2),
    dict(mode="uncompressed", d=256, momentum_type="virtual"),
], ids=lambda kw: f"{kw['mode']}-{kw.get('error_type', 'none')}")
def test_server_step_sparse_matches_dense(mode_kw):
    """The engine's hot path (server_step_sparse + apply_delta scatter) must
    be BIT-IDENTICAL to the dense contract (server_step + pflat - delta):
    x - 0.0 == x and x + (-v) == x - v in IEEE, and top-k indices are
    unique — so any drift here is a real bug, not float noise."""
    d = mode_kw["d"]
    cfg = _cfg(**mode_kw)
    rng = np.random.RandomState(3)
    g = jnp.asarray(rng.randn(d).astype(np.float32))
    cstate = jax.tree.map(  # one client's slice of the per-client state
        lambda x: x[0], modes.init_client_state(cfg, num_clients=1)) or {}
    wire, _ = modes.client_compress(cfg, g, cstate)
    agg = modes.aggregate(cfg, jax.tree.map(lambda x: x[None], wire))
    pflat = jnp.asarray(rng.randn(d).astype(np.float32))
    lr = jnp.float32(0.1)

    # two rounds so momentum/error state differences would compound
    s_dense = modes.init_server_state(cfg)
    s_sparse = jax.tree.map(jnp.copy, s_dense)
    for _ in range(2):
        delta_dense, s_dense = modes.server_step(cfg, agg, s_dense, lr)
        p_dense = pflat - delta_dense
        delta_wire, s_sparse = modes.server_step_sparse(cfg, agg, s_sparse, lr)
        p_sparse = modes.apply_delta(pflat, delta_wire)
        np.testing.assert_array_equal(np.asarray(p_dense), np.asarray(p_sparse))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            s_dense, s_sparse)
        # downlink support accounting must agree with the densified delta
        np.testing.assert_array_equal(
            np.asarray(modes.delta_support(d, delta_wire)),
            np.count_nonzero(np.asarray(delta_dense)))
        pflat = p_sparse


def test_topk_recall_knob():
    """topk_recall plumbing: validation bounds, and the recall kwarg reaches
    approx_max_k (on CPU the lowering is exact regardless, so this pins the
    wiring + exact-mode independence, not the recall behavior itself)."""
    with pytest.raises(ValueError):
        _cfg(mode="true_topk", k=2, topk_recall=1.5)
    with pytest.raises(ValueError):
        _cfg(mode="true_topk", k=2, topk_recall=0.0)
    v = jnp.asarray(np.random.RandomState(0).randn(64).astype(np.float32))
    i1, v1 = modes.topk_dense(v, 4, "approx", recall=0.99)
    i2, v2 = modes.topk_dense(v, 4, "exact")
    np.testing.assert_array_equal(np.sort(np.asarray(i1)), np.sort(np.asarray(i2)))
    # values must be the ORIGINAL (signed) coordinates, not |.| scores
    np.testing.assert_array_equal(np.sort(np.asarray(v1)), np.sort(np.asarray(v2)))
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v)[np.asarray(i1)])


def test_topk_oversample_matches_exact():
    """impl="oversample" (approx 4k-preselect + exact refine) must select
    the exact top-k set whenever the preselect keeps the true top-k — on
    CPU the approx lowering IS exact, so this pins the plumbing (index
    mapping through the candidate gather, value gather, d <= 4k fallback);
    the recall behavior itself is a TPU question answered by the
    paper-scale arm."""
    rng = np.random.RandomState(11)
    v = jnp.asarray(rng.randn(4096).astype(np.float32))
    i_o, v_o = modes.topk_dense(v, 32, "oversample")
    i_e, v_e = modes.topk_dense(v, 32, "exact")
    np.testing.assert_array_equal(np.sort(np.asarray(i_o)), np.sort(np.asarray(i_e)))
    np.testing.assert_array_equal(np.asarray(v_o), np.asarray(v)[np.asarray(i_o)])
    # 4k >= d: falls back to exact outright
    i_s, _ = modes.topk_dense(v[:100], 32, "oversample")
    i_x, _ = modes.topk_dense(v[:100], 32, "exact")
    np.testing.assert_array_equal(np.sort(np.asarray(i_s)), np.sort(np.asarray(i_x)))
    # and the sketch-space path accepts it end-to-end
    cfg = _cfg(mode="sketch", d=2048, k=8, num_rows=3, num_cols=256,
               momentum_type="virtual", error_type="virtual",
               topk_impl="oversample")
    sstate = modes.init_server_state(cfg)
    g = np.zeros(2048, np.float32)
    g[[5, 77, 900, 1500]] = [5.0, -6.0, 4.0, 3.0]
    wire, _ = modes.client_compress(cfg, jnp.asarray(g), {})
    agg = modes.aggregate(cfg, {"table": wire["table"][None]})
    delta, _ = modes.server_step(cfg, agg, sstate, jnp.float32(1.0))
    got = np.nonzero(np.asarray(delta))[0]
    assert {5, 77, 900, 1500} <= set(got.tolist())


def test_apply_delta_out_of_range_indices_are_inert():
    """Regression (advisor finding): idx >= d used to CLIP to d-1 and apply
    its val there, silently corrupting the last parameter; only idx < 0 was
    zeroed. Both sides out of range must contribute nothing."""
    p = jnp.arange(8, dtype=jnp.float32)
    delta = {
        "idx": jnp.array([2, -1, 8, 100], dtype=jnp.int32),
        "vals": jnp.array([1.0, 5.0, 7.0, 9.0], dtype=jnp.float32),
    }
    out = np.asarray(modes.apply_delta(p, delta))
    expected = np.asarray(p).copy()
    expected[2] -= 1.0  # the one in-range pair
    np.testing.assert_array_equal(out, expected)
    assert out[-1] == 7.0  # pflat[d-1] no longer absorbs clipped indices


def test_to_dense_out_of_range_indices_are_inert():
    """Same bound contract as apply_delta for the parallel sparse consumer:
    idx >= d must contribute nothing, not fold onto vector[d-1]."""
    from commefficient_tpu.sketch import csvec

    out = np.asarray(csvec.to_dense(
        4,
        jnp.array([1, -1, 4, 9], dtype=jnp.int32),
        jnp.array([2.0, 5.0, 7.0, 9.0], dtype=jnp.float32),
    ))
    np.testing.assert_array_equal(out, [0.0, 2.0, 0.0, 0.0])
