"""Client-dropout (straggler simulation) tests. The reference has NO failure
handling (SURVEY.md §5: "a dead worker hangs the run"); EngineConfig.
client_dropout is rebuild-side robustness: each sampled client independently
drops before aggregation, survivors are mean/sum-weighted, metrics count
survivors only, and stateful modes keep dropped clients' rows untouched."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.flatten_util import ravel_pytree

from commefficient_tpu.federated import engine
from commefficient_tpu.modes import modes
from commefficient_tpu.modes.config import ModeConfig

from test_engine import _data, _ucfg, init_mlp, mlp_loss


def _step(cfg_kw, **eng_kw):
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    cfg = engine.EngineConfig(mode=ModeConfig(**{**cfg_kw, "d": d}), **eng_kw)
    state = engine.init_server_state(cfg, params, {})
    return cfg, state, jax.jit(engine.make_round_step(mlp_loss, cfg))


def _batch(key, W, n=4):
    data = _data(key, W * n)
    return jax.tree.map(lambda a: a.reshape((W, n) + a.shape[1:]), data)


def test_dropout_zero_is_identity():
    batch = _batch(jax.random.PRNGKey(1), 8)
    lr, rng = jnp.float32(0.1), jax.random.PRNGKey(7)
    _, s0, step0 = _step(_ucfg())
    _, s1, step1 = _step(_ucfg(), client_dropout=0.0)
    a, _, ma = step0(s0, batch, {}, lr, rng)
    b, _, mb = step1(s1, batch, {}, lr, rng)
    for x, y in zip(jax.tree.leaves(a["params"]), jax.tree.leaves(b["params"])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert ma["count"] == mb["count"]


def _expected_mask(cfg, rng, W):
    """Reproduce the engine's mask derivation (same pure function + streams)."""
    _, _, drop_rng = jax.random.split(rng, 3)
    return np.asarray(engine.participation_mask(drop_rng, W, cfg.client_dropout))


@pytest.mark.parametrize("mode_kw", [
    _ucfg(),
    dict(mode="sketch", k=16, num_rows=3, num_cols=1024,
         hash_family="rotation", momentum_type="virtual", error_type="virtual"),
])
def test_dropout_equals_survivor_only_round(mode_kw):
    """A dropped round must equal the round run on ONLY the survivors (mean
    aggregation is survivor-normalized, so the dropped clients' data can have
    no influence at all)."""
    W, lr, rng = 8, jnp.float32(0.1), jax.random.PRNGKey(3)
    batch = _batch(jax.random.PRNGKey(1), W)
    cfg, state, step = _step(mode_kw, client_dropout=0.4)
    mask = _expected_mask(cfg, rng, W)
    assert 0 < mask.sum() < W  # the seed produces a non-trivial mask

    out, _, metrics = step(state, batch, {}, lr, rng)

    # survivor-only reference: replicate survivors' updates via a plain mean.
    # Same per-client rngs as the engine (split of the same crng stream), so
    # gradient noise/dropout inside loss_fn matches client-for-client.
    crng, _, _ = jax.random.split(rng, 3)
    client_rngs = jax.random.split(crng, W)
    params = init_mlp(jax.random.PRNGKey(0))
    pflat, unravel = ravel_pytree(params)

    def gflat(cb, r):
        g = jax.grad(lambda p: mlp_loss(p, {}, cb, r)[0])(params)
        return ravel_pytree(g)[0]

    upds = jnp.stack([
        gflat(jax.tree.map(lambda a: a[i], batch), client_rngs[i])
        for i in range(W)
    ])
    surv_mean = (upds * mask[:, None]).sum(0) / mask.sum()
    mcfg = cfg.mode
    agg, _ = modes.client_compress(mcfg, surv_mean, {})
    agg = modes.aggregate(mcfg, jax.tree.map(lambda x: x[None], agg))
    delta, _ = modes.server_step(
        mcfg, agg, modes.init_server_state(mcfg), lr
    )
    want = unravel(pflat - delta)
    got = out["params"]
    for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6)

    # metrics count only the survivors' examples (4 per client)
    assert float(metrics["count"]) == pytest.approx(mask.sum() * 4)


def test_dropout_preserves_dropped_local_state():
    """local_topk with local error: a dropped client's persistent error row
    must come back bit-identical; survivors' rows must change."""
    W = 8
    cfg_kw = dict(mode="local_topk", k=8, momentum_type="none", error_type="local")
    cfg, state, step = _step(cfg_kw, client_dropout=0.5)
    batch = _batch(jax.random.PRNGKey(2), W)
    rng = jnp.asarray(jax.random.PRNGKey(11))
    mask = _expected_mask(cfg, rng, W)
    assert 0 < mask.sum() < W

    d = cfg.mode.d
    rows = {"error": jnp.arange(W * d, dtype=jnp.float32).reshape(W, d)}
    _, new_rows, _ = step(state, batch, rows, jnp.float32(0.1), rng)
    for i in range(W):
        same = np.array_equal(np.asarray(new_rows["error"][i]), np.asarray(rows["error"][i]))
        assert same == (mask[i] == 0.0), (i, mask[i])


def test_full_dropout_round_is_a_noop_update():
    """All clients dropped: zero aggregate, so uncompressed/no-momentum params
    are unchanged, and metrics are all zero."""
    W = 4
    cfg, state, step = _step(_ucfg(), client_dropout=0.999999)
    batch = _batch(jax.random.PRNGKey(1), W)
    out, _, metrics = step(state, batch, {}, jnp.float32(0.5), jax.random.PRNGKey(0))
    p0 = init_mlp(jax.random.PRNGKey(0))
    for a, b in zip(jax.tree.leaves(out["params"]), jax.tree.leaves(p0)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(metrics["count"]) == 0.0


def test_full_dropout_with_dp_noise_applies_no_update():
    """An empty cohort transmits nothing, so with DP noise active a fully-
    dropped round must release NOTHING — not a pure-noise update at full
    clip sensitivity (ADVICE r3: ungated noise there is ~num_workers x a
    normal round's std injected into params)."""
    W = 4
    cfg, state, step = _step(
        _ucfg(), client_dropout=0.999999, dp_clip=1.0, dp_noise=2.0
    )
    batch = _batch(jax.random.PRNGKey(1), W)
    out, _, metrics = step(state, batch, {}, jnp.float32(0.5), jax.random.PRNGKey(0))
    p0 = init_mlp(jax.random.PRNGKey(0))
    for a, b in zip(jax.tree.leaves(out["params"]), jax.tree.leaves(p0)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert float(metrics["participants"]) == 0.0


def test_partial_dropout_with_dp_noise_still_noises():
    """The empty-cohort gate must not disable noise on normal rounds."""
    W = 8
    batch = _batch(jax.random.PRNGKey(1), W)
    lr, rng = jnp.float32(0.1), jax.random.PRNGKey(3)
    _, s_noise, step_noise = _step(
        _ucfg(), client_dropout=0.4, dp_clip=1.0, dp_noise=1.0
    )
    _, s_clean, step_clean = _step(_ucfg(), client_dropout=0.4, dp_clip=1.0)
    a, _, ma = step_noise(s_noise, batch, {}, lr, rng)
    b, _, _ = step_clean(s_clean, batch, {}, lr, rng)
    assert 0 < float(ma["participants"]) < W
    flat_a = ravel_pytree(a["params"])[0]
    flat_b = ravel_pytree(b["params"])[0]
    assert not np.allclose(np.asarray(flat_a), np.asarray(flat_b))


def test_invalid_dropout_rejected():
    with pytest.raises(ValueError):
        _step(_ucfg(), client_dropout=1.0)
    with pytest.raises(ValueError):
        _step(_ucfg(), client_dropout=-0.1)


def test_dropout_comm_accounting_charges_survivors_only():
    """run_round's uplink must scale with the surviving cohort; down-link
    (broadcast) still reaches everyone."""
    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated.api import FederatedSession

    rngd = np.random.RandomState(0)
    n, din, dout = 64, 10, 4
    x = rngd.normal(size=(n, din)).astype(np.float32)
    y = rngd.randint(0, dout, size=n).astype(np.int32)
    ds = FedDataset(x, y, shard_iid(n, 16, rngd))
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size

    def make(dropout):
        return FederatedSession(
            train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss,
            params=jax.tree.map(jnp.copy, params),  # the step donates state
            net_state={}, mode_cfg=ModeConfig(**_ucfg(d=d)), train_set=ds,
            num_workers=8, local_batch_size=2, seed=5, client_dropout=dropout,
        )

    base = make(0.0).run_round(0.1)
    drop_sess = make(0.5)
    m = drop_sess.run_round(0.1)
    surv = m["participants"]
    assert 0 < surv < 8
    assert m["comm_up_mb"] == pytest.approx(base["comm_up_mb"] * surv / 8)
    assert m["comm_down_mb"] == pytest.approx(base["comm_down_mb"])
    assert m["comm_total_mb"] == pytest.approx(m["comm_up_mb"] + m["comm_down_mb"])


def test_dropout_sharded_equals_unsharded():
    """The participation mask derives from the step's rng INSIDE the compiled
    program; over the 8-device client mesh it must replicate identically, so
    sharded == unsharded holds with dropout active (same contract as
    test_engine.py::test_sharded_equals_unsharded)."""
    from commefficient_tpu.parallel import mesh as meshlib
    from test_engine import _data as edata

    mesh = meshlib.make_mesh(8)
    data = edata(jax.random.PRNGKey(5), 64)
    w8 = jax.tree.map(lambda a: a.reshape((8, 8) + a.shape[1:]), data)
    lr, rng = jnp.float32(0.1), jax.random.PRNGKey(4)
    cfg, state, step = _step(_ucfg(), client_dropout=0.4)
    mask = _expected_mask(cfg, rng, 8)
    assert 0 < mask.sum() < 8

    ref, _, mref = step(state, w8, {}, lr, rng)
    _, state2, step2 = _step(_ucfg(), client_dropout=0.4)
    got, _, mgot = step2(state2, meshlib.shard_client_batch(mesh, w8), {}, lr, rng)
    for a, b in zip(jax.tree.leaves(got["params"]), jax.tree.leaves(ref["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
    assert float(mgot["participants"]) == float(mref["participants"]) == mask.sum()


def test_dropout_session_persistent_state_roundtrip():
    """Session-level composition: local_topk with client-local error state +
    dropout. The gather/scatter cycle must write dropped clients' rows back
    bit-identical while survivors' rows change."""
    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated.api import FederatedSession

    rngd = np.random.RandomState(1)
    n = 48
    x = rngd.normal(size=(n, 10)).astype(np.float32)
    y = rngd.randint(0, 4, size=n).astype(np.int32)
    ds = FedDataset(x, y, shard_iid(n, 12, rngd))
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    sess = FederatedSession(
        train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss, params=params,
        net_state={}, train_set=ds, num_workers=8, local_batch_size=2,
        seed=9, client_dropout=0.5,
        mode_cfg=ModeConfig(mode="local_topk", d=d, k=8, momentum_type="none",
                            error_type="local", num_clients=12),
    )
    # seed the persistent state with recognizable values
    marked = jax.tree.map(
        lambda a: jnp.arange(a.size, dtype=a.dtype).reshape(a.shape) * 1e-3,
        sess.client_state,
    )
    sess.client_state = marked
    before = np.asarray(marked["error"])

    # reproduce the round's sampled ids and mask (session rng protocol)
    ids = sess.train_set.sample_clients(np.random.RandomState(9), 8)
    m = sess.run_round(0.1)
    after = np.asarray(sess.client_state["error"])

    surv = int(m["participants"])
    assert 0 < surv < 8
    changed = {i for i in range(12) if not np.array_equal(before[i], after[i])}
    # exactly the surviving sampled clients changed
    assert changed <= set(ids.tolist())
    assert len(changed) == surv
    # unsampled clients untouched
    for i in set(range(12)) - set(ids.tolist()):
        np.testing.assert_array_equal(before[i], after[i])
