"""Property tests for the count-sketch library (SURVEY.md §4 unit list):
linearity, seed-determinism, block-count invariance, heavy-hitter recovery,
unbiasedness of single-coordinate estimates, sparse==dense sketching."""

from dataclasses import replace as dataclasses_replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.sketch import csvec as csvec_mod
from commefficient_tpu.sketch import (
    CSVecSpec,
    query,
    query_all,
    sketch_sparse,
    sketch_vec,
    to_dense,
    unsketch_topk,
)

SPEC = CSVecSpec(d=5000, c=1000, r=5, num_blocks=1, seed=7)


def _randn(key, shape):
    return jax.random.normal(jax.random.PRNGKey(key), shape)


def test_linearity():
    a = _randn(0, (SPEC.d,))
    b = _randn(1, (SPEC.d,))
    np.testing.assert_allclose(
        sketch_vec(SPEC, a) + sketch_vec(SPEC, b),
        sketch_vec(SPEC, a + b),
        rtol=1e-5,
        atol=1e-5,
    )


def test_seed_determinism_and_difference():
    v = _randn(2, (SPEC.d,))
    t1 = sketch_vec(SPEC, v)
    t2 = sketch_vec(CSVecSpec(**{**SPEC.__dict__}), v)
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    other = sketch_vec(CSVecSpec(d=SPEC.d, c=SPEC.c, r=SPEC.r, seed=8), v)
    assert not np.allclose(np.asarray(t1), np.asarray(other))


@pytest.mark.parametrize("num_blocks", [2, 4, 7])
def test_block_invariance(num_blocks):
    """num_blocks is a memory knob, not a semantics knob."""
    v = _randn(3, (SPEC.d,))
    blocked = CSVecSpec(d=SPEC.d, c=SPEC.c, r=SPEC.r, num_blocks=num_blocks, seed=SPEC.seed)
    np.testing.assert_allclose(
        np.asarray(sketch_vec(SPEC, v)), np.asarray(sketch_vec(blocked, v)), rtol=1e-5, atol=1e-5
    )
    t = sketch_vec(SPEC, v)
    np.testing.assert_allclose(
        np.asarray(query_all(SPEC, t)), np.asarray(query_all(blocked, t)), rtol=1e-5, atol=1e-5
    )
    ib, vb = unsketch_topk(blocked, t, 50)
    i1, v1 = unsketch_topk(SPEC, t, 50)
    assert set(np.asarray(ib).tolist()) == set(np.asarray(i1).tolist())


def test_heavy_hitter_recovery():
    """Plant k heavy coords in noise; assert exact recovery (SURVEY.md §4)."""
    d, k = 20000, 20
    spec = CSVecSpec(d=d, c=4000, r=5, num_blocks=4, seed=11)
    rng = np.random.RandomState(0)
    v = rng.normal(0, 0.01, size=d).astype(np.float32)
    heavy_idx = rng.choice(d, size=k, replace=False)
    heavy_vals = rng.choice([-10.0, 10.0], size=k) * rng.uniform(1.0, 2.0, size=k)
    v[heavy_idx] = heavy_vals
    idx, vals = unsketch_topk(spec, sketch_vec(spec, jnp.asarray(v)), k)
    assert set(np.asarray(idx).tolist()) == set(heavy_idx.tolist())
    # recovered values close to true values
    order = np.argsort(np.asarray(idx))
    torder = np.argsort(heavy_idx)
    np.testing.assert_allclose(
        np.asarray(vals)[order], heavy_vals[torder].astype(np.float32), rtol=0.15, atol=0.3
    )


def test_threshold_query():
    """unsketch_threshold (CSVec._findHHThr parity): every coordinate with
    |estimate| >= thr is returned, sub-threshold ones padded out."""
    from commefficient_tpu.sketch import unsketch_threshold

    d, k = 20000, 20
    spec = CSVecSpec(d=d, c=4000, r=5, num_blocks=4, seed=11)
    rng = np.random.RandomState(0)
    v = rng.normal(0, 0.01, size=d).astype(np.float32)
    heavy_idx = rng.choice(d, size=k, replace=False)
    v[heavy_idx] = rng.choice([-10.0, 10.0], size=k) * rng.uniform(1.0, 2.0, size=k)
    t = sketch_vec(spec, jnp.asarray(v))
    idx, vals = unsketch_threshold(spec, t, thr=5.0, max_k=3 * k)
    got = set(np.asarray(idx)[np.asarray(idx) >= 0].tolist())
    # exactly the planted heavies pass thr=5 (|vals| >= 10 planted, noise ~0.01)
    assert got == set(heavy_idx.tolist())
    assert np.all(np.abs(np.asarray(vals)[np.asarray(idx) >= 0]) >= 5.0)
    assert np.all(np.asarray(vals)[np.asarray(idx) < 0] == 0.0)
    # a threshold above everything returns an empty (all-padding) result
    idx2, _ = unsketch_threshold(spec, t, thr=1e6, max_k=8)
    assert np.all(np.asarray(idx2) == -1)


def test_unbiasedness():
    """Median-of-rows estimate of a fixed coord, averaged over seeds, ≈ truth."""
    d = 2000
    v = np.zeros(d, dtype=np.float32)
    v[123] = 5.0
    v[777] = -3.0
    rng = np.random.RandomState(1)
    v += rng.normal(0, 0.5, size=d).astype(np.float32)
    ests = []
    for seed in range(30):
        spec = CSVecSpec(d=d, c=500, r=5, seed=seed)
        t = sketch_vec(spec, jnp.asarray(v))
        ests.append(float(query(spec, t, jnp.array([123]))[0]))
    assert abs(np.mean(ests) - float(v[123])) < 0.3


def test_sparse_equals_dense():
    d = 1000
    spec = CSVecSpec(d=d, c=300, r=3, seed=5)
    idx = jnp.array([3, 500, 999, -1], dtype=jnp.int32)  # -1 = padding, ignored
    vals = jnp.array([1.5, -2.0, 4.0, 100.0], dtype=jnp.float32)
    dense = to_dense(d, idx, vals)
    np.testing.assert_allclose(
        np.asarray(sketch_sparse(spec, idx, vals)),
        np.asarray(sketch_vec(spec, dense)),
        rtol=1e-6,
        atol=1e-6,
    )


def test_to_dense_ignores_padding():
    dense = to_dense(10, jnp.array([-1, 2]), jnp.array([9.0, 1.0]))
    np.testing.assert_array_equal(np.asarray(dense), np.eye(10, dtype=np.float32)[2])


# ------------------------------------------------------- rotation family

ROT = CSVecSpec(d=5000, c=1000, r=5, seed=7, family="rotation")


def test_rotation_fast_paths_match_generic():
    """The roll-based dense accumulate/query must agree exactly with the
    generic (idx → buckets/signs) path shared with sparse sketching."""
    v = _randn(0, (ROT.d,))
    all_idx = jnp.arange(ROT.d, dtype=jnp.int32)
    np.testing.assert_allclose(
        np.asarray(sketch_vec(ROT, v)),  # fast path
        np.asarray(sketch_sparse(ROT, all_idx, v)),  # generic scatter path
        rtol=1e-5,
        atol=1e-5,
    )
    t = sketch_vec(ROT, v)
    np.testing.assert_allclose(
        np.asarray(query_all(ROT, t)),  # fast path
        np.asarray(query(ROT, t, all_idx)),  # generic gather path
        rtol=1e-6,
        atol=1e-6,
    )
    i_fast, v_fast = unsketch_topk(ROT, t, 50)
    est = np.asarray(query_all(ROT, t))
    i_ref = np.argsort(-np.abs(est))[:50]
    assert set(np.asarray(i_fast).tolist()) == set(i_ref.tolist())
    np.testing.assert_allclose(np.sort(np.asarray(v_fast)), np.sort(est[i_ref]), rtol=1e-6)


def test_rotation_linearity_and_determinism():
    a = _randn(1, (ROT.d,))
    b = _randn(2, (ROT.d,))
    np.testing.assert_allclose(
        sketch_vec(ROT, a) + sketch_vec(ROT, b), sketch_vec(ROT, a + b), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_array_equal(
        np.asarray(sketch_vec(ROT, a)), np.asarray(sketch_vec(CSVecSpec(**ROT.__dict__), a))
    )
    other = sketch_vec(dataclasses_replace(ROT, seed=8), a)
    assert not np.allclose(np.asarray(sketch_vec(ROT, a)), np.asarray(other))


def test_rotation_heavy_hitter_recovery():
    d, k = 20000, 20
    spec = CSVecSpec(d=d, c=4000, r=5, seed=11, family="rotation")
    rng = np.random.RandomState(0)
    v = rng.normal(0, 0.01, size=d).astype(np.float32)
    heavy_idx = rng.choice(d, size=k, replace=False)
    heavy_vals = rng.choice([-10.0, 10.0], size=k) * rng.uniform(1.0, 2.0, size=k)
    v[heavy_idx] = heavy_vals
    idx, vals = unsketch_topk(spec, sketch_vec(spec, jnp.asarray(v)), k)
    assert set(np.asarray(idx).tolist()) == set(heavy_idx.tolist())
    order = np.argsort(np.asarray(idx))
    torder = np.argsort(heavy_idx)
    np.testing.assert_allclose(
        np.asarray(vals)[order], heavy_vals[torder].astype(np.float32), rtol=0.15, atol=0.3
    )


def test_rotation_unbiasedness():
    d = 2000
    v = np.zeros(d, dtype=np.float32)
    v[123] = 5.0
    v[777] = -3.0
    rng = np.random.RandomState(1)
    v += rng.normal(0, 0.5, size=d).astype(np.float32)
    ests = []
    for seed in range(30):
        spec = CSVecSpec(d=d, c=500, r=5, seed=seed, family="rotation")
        t = sketch_vec(spec, jnp.asarray(v))
        ests.append(float(query(spec, t, jnp.array([123]))[0]))
    assert abs(np.mean(ests) - float(v[123])) < 0.3


def test_rotation_d_not_multiple_of_c():
    """Partial last slab: padding must not contaminate sketches or top-k."""
    spec = CSVecSpec(d=1234, c=500, r=3, seed=3, family="rotation")
    v = _randn(5, (spec.d,))
    t = sketch_vec(spec, v)
    all_idx = jnp.arange(spec.d, dtype=jnp.int32)
    np.testing.assert_allclose(
        np.asarray(t), np.asarray(sketch_sparse(spec, all_idx, v)), rtol=1e-5, atol=1e-5
    )
    idx, vals = unsketch_topk(spec, t, 40)
    assert np.all(np.asarray(idx) < spec.d) and np.all(np.asarray(idx) >= 0)


def test_jit_and_vmap():
    """Sketch ops must compose with jit/vmap — they live inside the round step."""
    spec = CSVecSpec(d=256, c=64, r=3, num_blocks=2, seed=0)
    vs = _randn(4, (6, spec.d))
    tables = jax.jit(jax.vmap(lambda v: sketch_vec(spec, v)))(vs)
    assert tables.shape == (6, spec.r, spec.c)
    summed = tables.sum(0)
    np.testing.assert_allclose(
        np.asarray(summed), np.asarray(sketch_vec(spec, vs.sum(0))), rtol=1e-4, atol=1e-4
    )
    idx, vals = jax.jit(lambda t: unsketch_topk(spec, t, 10))(summed)
    assert idx.shape == (10,) and vals.shape == (10,)


def test_unsketch_single_shot_matches_chunked_scan(monkeypatch):
    """The single-shot unsketch (affordable [d] transient) and the
    memory-bounding slab scan must recover the same top-k set with the same
    values — for every impl, both rotation-family routes (on CPU the
    approx lowering is exact, so approx/oversample pin the PRESELECT
    plumbing of the chunked path: masking, index mapping, carry merge)."""
    spec = CSVecSpec(d=10000, c=1024, r=3, seed=3, family="rotation")
    rng = np.random.RandomState(4)
    v = rng.normal(0, 0.01, size=spec.d).astype(np.float32)
    v[rng.choice(spec.d, 30, replace=False)] = 25.0
    t = sketch_vec(spec, jnp.asarray(v))

    for impl in ("exact", "approx", "oversample"):
        monkeypatch.setattr(
            csvec_mod, "UNSKETCH_SINGLE_SHOT_BYTES", 1 << 30)
        i_single, v_single = unsketch_topk(spec, t, 30, impl=impl)
        monkeypatch.setattr(csvec_mod, "UNSKETCH_SINGLE_SHOT_BYTES", 0)
        i_scan, v_scan = unsketch_topk(spec, t, 30, impl=impl)
        assert set(np.asarray(i_single).tolist()) == \
            set(np.asarray(i_scan).tolist()), impl
        np.testing.assert_allclose(
            np.sort(np.asarray(v_single)), np.sort(np.asarray(v_scan)),
            rtol=1e-6)


def test_mask_transmitted_matches_unfused():
    """The fused masking tail (one hash evaluation) must be BIT-IDENTICAL to
    the unfused sequence E -= sketch_sparse(vals); vvals = query(V);
    V -= sketch_sparse(vvals) — including idx = -1 padding entries, whose
    contribution is exactly zero on both paths."""
    for family in ("rotation", "random"):
        spec = CSVecSpec(d=4096, c=512, r=5, seed=9, family=family)
        rng = np.random.RandomState(2)
        V = jnp.asarray(rng.randn(spec.r, spec.c).astype(np.float32))
        E = jnp.asarray(rng.randn(spec.r, spec.c).astype(np.float32))
        idx = jnp.asarray(
            np.concatenate([rng.choice(spec.d, 30, replace=False),
                            [-1, -1]]).astype(np.int32))
        vals = jnp.asarray(rng.randn(32).astype(np.float32))

        E_ref = E - sketch_sparse(spec, idx, vals)
        vvals = query(spec, V, idx)
        V_ref = V - sketch_sparse(spec, idx, vvals)

        V_f, E_f = csvec_mod.mask_transmitted(spec, V, E, idx, vals)
        np.testing.assert_array_equal(np.asarray(V_ref), np.asarray(V_f), err_msg=family)
        np.testing.assert_array_equal(np.asarray(E_ref), np.asarray(E_f), err_msg=family)
