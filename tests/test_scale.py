"""C1M scale-out serving (serve/scale/): event-loop transport, sharded
ingest, and the two-tier edge-aggregation tree.

The acceptance pins live here:

- the EDGE-TREE merge (each edge ordered-sums its hash-shard's validated
  tables, the root folds the forwarded [E, r, c] partials in fixed edge
  order) is BIT-identical — params + every logged row — to the FLAT merge
  of the same edge-armed session over the same surviving cohort, under
  randomized arrival orders, edge counts, and straggler/drop patterns,
  fused AND client-sharded, inproc AND socket;
- an edge dying mid-round == its whole hash-shard of the cohort dropped,
  bitwise, with the requeue machinery re-serving the clients;
- preempt -> resume mid-run through the edge-tree path is bit-identical to
  the uninterrupted twin (the CLI path);
- the EVENT-LOOP transport makes the same admission decisions as the
  threaded reference (shared LineProtocol): accept/dup/uninvited/
  out-of-round, chunked payload reassembly, mid-send death == MALFORMED
  partial sequence, read-deadline reaping, byte-flood cap, connection cap;
- the SHARDED ingest routes by client-id hash, keeps one admission truth
  (the shared queue), and surfaces per-shard counters + load-scaled
  SHEDDING retry-after hints in /metrics and /metrics.prom.
"""

from __future__ import annotations

import collections
import json
import socket
import time
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated.api import FederatedSession
from commefficient_tpu.federated import engine
from commefficient_tpu.modes import modes
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.resilience import FaultPlan
from commefficient_tpu.serve.ingest import (
    ACCEPTED,
    DUPLICATE,
    IngestQueue,
    NOT_INVITED,
    OUT_OF_ROUND,
    PayloadPolicy,
    SHEDDING,
    Submission,
)
from commefficient_tpu.serve.scale.edge import (
    EdgeTree,
    assign_edges,
    table_norms_host,
)
from commefficient_tpu.serve.scale.eventloop import EventLoopTransport
from commefficient_tpu.serve.scale.shard import ShardedIngest, shard_for
from commefficient_tpu.serve.service import AggregationService, ServeConfig
from commefficient_tpu.serve.traffic import TraceConfig, TrafficGenerator
from commefficient_tpu.serve.transport import (
    SocketTransport,
    abort_over_socket,
    submit_over_socket,
)

LR = 0.05


# ------------------------------------------------------------------ fixtures


def _quad_loss(params, net_state, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
    mask = batch["mask"]
    count = jnp.maximum(mask.sum(), 1.0)
    per_ex = (err ** 2).sum(-1)
    return (per_ex * mask).sum() / count, {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()}}


def _tiny_session(serve_edges=0, clip=0.0, shards=1, seed=0, workers=4,
                  merge_policy="sum", merge_trim=0, fault_plan=None):
    rs = np.random.RandomState(0)
    x = rs.randn(96, 6).astype(np.float32)
    w_true = rs.randn(6, 3).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int32)
    train = FedDataset(x, y, shard_iid(len(x), 12, np.random.RandomState(1)))
    params = {"w": jnp.asarray(rs.randn(6, 3).astype(np.float32) * 0.1),
              "b": jnp.zeros(3)}
    d = ravel_pytree(params)[0].size
    mc = ModeConfig(mode="sketch", d=d, k=4, num_rows=3, num_cols=16,
                    momentum_type="virtual", error_type="virtual")
    return FederatedSession(
        train_loss_fn=_quad_loss, eval_loss_fn=_quad_loss,
        params=params, net_state={}, mode_cfg=mc, train_set=train,
        num_workers=workers, local_batch_size=4, seed=seed,
        wire_payloads=True, serve_edges=serve_edges,
        client_update_clip=clip, client_shards=shards,
        merge_policy=merge_policy, merge_trim=merge_trim,
        fault_plan=fault_plan,
    )


def _serve(session, rounds, edges=0, transport="inproc", quorum=3,
           trace_seed=5, deadline=4.0):
    """Drive served rounds through the real dispatch shape; returns the
    metric rows."""
    cfg = ServeConfig(quorum=quorum, deadline_s=deadline,
                      transport=transport, payload="sketch", edges=edges)
    svc = AggregationService(
        session, cfg,
        traffic=TrafficGenerator(
            TraceConfig(population=session.train_set.num_clients,
                        seed=trace_seed))).start()
    rows = []
    try:
        src = svc.source()
        for _ in range(rounds):
            prep = src.next()
            rows.append(session.commit_round(
                session.dispatch_round(prep, LR))[0])
            src.on_dispatched(session.round - 1)
            src.on_committed(session.round)
        src.stop()
        with session.mutate_lock:
            rng_state, rng_key = session.rng_snapshot
            session.rng.set_state(rng_state)
            session._rng_key = rng_key
            session._requeue = collections.deque(session._requeue_committed)
            session._requeue_enqueued = dict(
                session._requeue_ages_committed)
    finally:
        svc.close()
    return rows


def _assert_params_equal(sa, sb):
    for x, y in zip(
        jax.tree.leaves(jax.device_get(sa.state["params"])),
        jax.tree.leaves(jax.device_get(sb.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _assert_rows_equal(ra, rb):
    for a, b in zip(ra, rb):
        assert set(a) == set(b)
        for k in a:
            assert a[k] == b[k], (k, a[k], b[k])


def _sub(cid, rnd=0, latency=0.1, payload=None):
    return Submission(client_id=cid, round=rnd, latency_s=latency,
                      payload=payload)


# ------------------------------------------------- edge fold arithmetic


def test_edge_grouped_sum_matches_per_edge_folds_bitwise():
    """The load-bearing arithmetic property: the in-program grouped fold
    over the full stack == per-edge shard-local folds + the fixed-order
    partial merge, BITWISE, for randomized tables/masks/assignments —
    the mechanism the end-to-end pin rests on."""
    fold = jax.jit(lambda ts, ms: jax.lax.scan(
        lambda a, x: (a + jnp.where(x[1] > 0, x[0], jnp.zeros_like(x[0])),
                      None),
        jnp.zeros(ts.shape[1:], ts.dtype),
        (ts, ms))[0])
    for seed in range(5):
        rs = np.random.RandomState(seed)
        W, r, c = 8, 3, 7
        E = int(rs.randint(2, 5))
        scale = np.logspace(-3, 3, W).reshape(-1, 1, 1).astype(np.float32)
        tables = (rs.randn(W, r, c).astype(np.float32) * scale)
        live = (rs.rand(W) > 0.3).astype(np.float32)
        assign = rs.randint(0, E, W).astype(np.int32)
        grouped = np.asarray(modes.edge_grouped_sum(
            jnp.asarray(tables), jnp.asarray(live), jnp.asarray(assign), E))
        partials = []
        for e in range(E):
            idx = np.flatnonzero(assign == e)
            partials.append(np.asarray(fold(jnp.asarray(tables[idx]),
                                            jnp.asarray(live[idx]))))
        tree = np.asarray(modes.merge_edge_partials(
            jnp.asarray(np.stack(partials))))
        np.testing.assert_array_equal(grouped, tree)


def test_table_norms_host_partition_invariant():
    rs = np.random.RandomState(3)
    tables = rs.randn(9, 3, 5).astype(np.float32)
    full = table_norms_host(tables)
    assign = assign_edges(np.arange(100, 109), 3)
    for e in range(3):
        idx = np.flatnonzero(assign == e)
        np.testing.assert_array_equal(full[idx], table_norms_host(tables[idx]))
    assert table_norms_host(np.zeros((0, 3, 5), np.float32)).shape == (0,)


def test_assign_edges_matches_shard_routing():
    ids = np.arange(1000, 1050)
    assign = assign_edges(ids, 4)
    assert assign.dtype == np.int32
    for i, cid in enumerate(ids):
        assert assign[i] == shard_for(int(cid), 4)
    # uses more than one edge on any reasonable cohort
    assert len(set(assign.tolist())) > 1


# ------------------------------------- THE pin: edge tree == flat, bitwise


@pytest.mark.parametrize("clip,shards,edges,quorum,trace_seed", [
    (0.0, 1, 2, 3, 5),    # fused, quarantine off
    (3.0, 1, 3, 3, 7),    # fused, quarantine armed, 3 edges
    (3.0, 2, 2, 3, 11),   # client-sharded session
    (0.0, 1, 4, 2, 13),   # deep short-quorum drops (straggler patterns)
])
def test_edge_tree_merge_equals_flat_merge_bitwise(clip, shards, edges,
                                                   quorum, trace_seed):
    """THE acceptance pin: the two-tier edge-tree serving path (partials
    crossing the tree) is bit-identical — params + every logged row — to
    the flat serving path of the same edge-armed session, across
    randomized arrival orders (trace seeds), edge counts, quarantine
    armed/off, short-quorum straggler/no-show patterns, and client
    sharding."""
    sa = _tiny_session(serve_edges=edges, clip=clip, shards=shards)
    ra = _serve(sa, 4, edges=edges, quorum=quorum, trace_seed=trace_seed)
    sb = _tiny_session(serve_edges=edges, clip=clip, shards=shards)
    rb = _serve(sb, 4, edges=0, quorum=quorum, trace_seed=trace_seed)
    _assert_params_equal(sa, sb)
    _assert_rows_equal(ra, rb)


def test_edge_tree_over_socket_equals_inproc_bitwise():
    """The pin holds over the REAL loopback socket wire (frames, checksums,
    the gauntlet) — float32 serialization is exact, so the edge-tree
    socket round is bitwise the inproc one."""
    sa = _tiny_session(serve_edges=2)
    ra = _serve(sa, 3, edges=2, transport="socket")
    sb = _tiny_session(serve_edges=2)
    rb = _serve(sb, 3, edges=2, transport="inproc")
    _assert_params_equal(sa, sb)
    _assert_rows_equal(ra, rb)


def test_edge_death_equals_shard_dropped_bitwise():
    """An edge killed mid-round == every client of its hash-shard dropped
    (client_drop at the same positions), bitwise, and the casualties go
    through the requeue machinery."""
    E, kill_round, dead_edge = 2, 1, 1
    plan = FaultPlan.parse(f"edge_kill@{kill_round}:edges={dead_edge}")
    sa = _tiny_session(serve_edges=E, fault_plan=plan)
    # derive the doomed positions the same way the tree will: the round's
    # cohort is a pure function of the session's sampling stream
    probe = _tiny_session(serve_edges=E)
    ids_by_round = [probe.sample_cohort(r) for r in range(2)]
    doomed = np.flatnonzero(
        assign_edges(ids_by_round[kill_round], E) == dead_edge)
    assert len(doomed) > 0, "hash assignment left the dead edge empty"
    drop_spec = "+".join(str(int(p)) for p in doomed)
    plan_b = FaultPlan.parse(f"client_drop@{kill_round}:clients={drop_spec}")
    sb = _tiny_session(serve_edges=E, fault_plan=plan_b)
    ra = _serve(sa, 3, edges=E, quorum=0)
    rb = _serve(sb, 3, edges=E, quorum=0)
    _assert_params_equal(sa, sb)
    _assert_rows_equal(ra, rb)
    # the whole shard was masked + the requeue machinery saw them
    assert ra[kill_round]["clients_dropped"] >= len(doomed)
    assert ra[kill_round]["requeue_depth"] >= len(doomed)


def test_robust_merge_forces_forward_mode_and_stays_bitwise(capsys):
    """--merge_policy trimmed with the edge tree: edges FORWARD per-client
    tables (loud note), the plain robust program dispatches, and the
    tree run is bitwise the flat robust run."""
    sa = _tiny_session(merge_policy="trimmed", merge_trim=1)
    ra = _serve(sa, 3, edges=2)
    note = capsys.readouterr().err
    assert "FORWARDS its shard's validated tables" in note
    sb = _tiny_session(merge_policy="trimmed", merge_trim=1)
    rb = _serve(sb, 3, edges=0)
    _assert_params_equal(sa, sb)
    _assert_rows_equal(ra, rb)


def test_edge_config_validation():
    # engine-side: serve_edges needs the wire, rejects robust/async/layer
    with pytest.raises(ValueError, match="wire_payloads"):
        engine.EngineConfig(
            mode=ModeConfig(mode="sketch", d=8, k=2, num_rows=2,
                            num_cols=8), serve_edges=2)
    with pytest.raises(ValueError, match="robust"):
        _tiny_session(serve_edges=2, merge_policy="median")
    # service-side: the topology needs a session compiled for it
    s = _tiny_session(serve_edges=0)
    with pytest.raises(ValueError, match="serve_edges"):
        AggregationService(
            s, ServeConfig(quorum=3, transport="inproc", payload="sketch",
                           edges=2),
            traffic=TrafficGenerator(TraceConfig(population=12)))
    with pytest.raises(ValueError, match="announce path has none"):
        AggregationService(
            s, ServeConfig(quorum=3, transport="inproc", edges=2),
            traffic=TrafficGenerator(TraceConfig(population=12)))
    with pytest.raises(ValueError, match="one edge IS the flat merge"):
        AggregationService(
            s, ServeConfig(quorum=3, transport="inproc", payload="sketch",
                           edges=1),
            traffic=TrafficGenerator(TraceConfig(population=12)))
    # edge_kill context validation
    plan = FaultPlan.parse("edge_kill@1:edges=0")
    with pytest.raises(ValueError, match="edge_kill can never fire"):
        plan.validate_edge_context(False)
    with pytest.raises(ValueError, match="can never fire"):
        plan.validate_edge_context(True, n_edges=0)
    plan.validate_edge_context(True, n_edges=2)
    with pytest.raises(ValueError, match="edge_kill"):
        FaultPlan.parse("edge_kill@1")  # edges= required


# --------------------------------------------- event-loop transport parity


def test_eventloop_admission_decisions_match_threaded():
    """Same LineProtocol, same queue: every admission decision the
    threaded transport returns, the reactor returns."""
    for cls in (SocketTransport, EventLoopTransport):
        q = IngestQueue(capacity=16)
        t = cls(q, read_deadline_s=2.0)
        t.start()
        try:
            q.open_round(0, [1, 2, 3])
            assert submit_over_socket(t.address, _sub(1)) == ACCEPTED
            assert submit_over_socket(t.address, _sub(1)) == DUPLICATE
            assert submit_over_socket(t.address, _sub(9)) == NOT_INVITED
            assert submit_over_socket(t.address, _sub(2, rnd=7)) == \
                OUT_OF_ROUND
        finally:
            t.stop()
            q.shutdown()


def test_eventloop_chunked_payload_roundtrip_exact():
    q = IngestQueue(capacity=8,
                    payload_policy=PayloadPolicy(rows=2, cols=4096))
    t = EventLoopTransport(q, max_frame_bytes=4096, read_deadline_s=2.0)
    t.start()
    try:
        q.open_round(0, [7])
        tab = np.arange(2 * 4096, dtype=np.float32).reshape(2, 4096)
        assert submit_over_socket(
            t.address, _sub(7, payload=tab), max_frame_bytes=4096) == \
            ACCEPTED
        arr = q.arrivals(0)
        assert len(arr) == 1
        np.testing.assert_array_equal(arr[0].table, tab)
    finally:
        t.stop()
        q.shutdown()


def test_eventloop_mid_send_death_counts_malformed():
    """A connection that dies mid chunk-sequence admits nothing and the
    partial sequence counts MALFORMED when the deadline reaps it."""
    q = IngestQueue(capacity=8,
                    payload_policy=PayloadPolicy(rows=2, cols=4096))
    t = EventLoopTransport(q, max_frame_bytes=4096, read_deadline_s=0.3)
    t.start()
    try:
        q.open_round(0, [8])
        tab = np.ones((2, 4096), np.float32)
        abort_over_socket(t.address, _sub(8, payload=tab),
                          max_frame_bytes=4096)
        deadline = time.monotonic() + 5.0
        while (q.counters()["rejected_malformed"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert q.counters()["rejected_malformed"] >= 1
        assert q.arrivals(0) == []
    finally:
        t.stop()
        q.shutdown()


def test_eventloop_byte_flood_cut_off_at_cap():
    q = IngestQueue(capacity=8)
    t = EventLoopTransport(q, max_frame_bytes=2048, read_deadline_s=2.0)
    t.start()
    try:
        with socket.create_connection(t.address, timeout=5.0) as s:
            s.sendall(b"x" * 8192)  # newline-less flood
            s.settimeout(5.0)
            reply = b""
            while b"\n" not in reply:
                chunk = s.recv(4096)
                if not chunk:
                    break
                reply += chunk
        assert b"MALFORMED" in reply
        assert q.counters()["rejected_malformed"] >= 1
    finally:
        t.stop()
        q.shutdown()


def test_eventloop_connection_cap_refuses():
    q = IngestQueue(capacity=8)
    t = EventLoopTransport(q, read_deadline_s=5.0, max_conns=4)
    t.start()
    socks = []
    try:
        q.open_round(0, list(range(16)))
        for _ in range(4):
            s = socket.create_connection(t.address, timeout=5.0)
            socks.append(s)
            # one byte each so the reactor has registered the conn
            s.sendall(b"\n")
        deadline = time.monotonic() + 5.0
        while t.open_conns < 4 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert t.open_conns == 4
        # the 5th is accepted by the OS but closed by the reactor: a
        # round-trip on it must fail
        with pytest.raises((ConnectionError, OSError)):
            submit_over_socket(t.address, _sub(1), timeout_s=2.0)
    finally:
        for s in socks:
            s.close()
        t.stop()
        q.shutdown()


def test_eventloop_holds_many_concurrent_connections():
    """The scale claim in miniature: the reactor holds an order of
    magnitude more live connections than the threaded transport's default
    cap, on one thread, and still answers."""
    q = IngestQueue(capacity=4096)
    t = EventLoopTransport(q, read_deadline_s=30.0)
    t.start()
    socks = []
    try:
        q.open_round(0, list(range(2000)))
        n = 1500  # > 10x DEFAULT_MAX_CONNS_THREADED (128)
        for _ in range(n):
            socks.append(socket.create_connection(t.address, timeout=10.0))
        # every connection live at once, then each submits
        for i, s in enumerate(socks):
            s.sendall(json.dumps(
                {"client_id": i, "round": 0, "latency_s": 0.1}
            ).encode() + b"\n")
        got = 0
        for s in socks:
            s.settimeout(30.0)
            buf = b""
            while b"\n" not in buf:
                chunk = s.recv(4096)
                if not chunk:
                    break
                buf += chunk
            if b"ACCEPTED" in buf:
                got += 1
        assert got == n
        assert q.counters()["accepted"] == n
    finally:
        for s in socks:
            s.close()
        t.stop()
        q.shutdown()


def test_standalone_reactor_publishes_no_shard_series():
    """A plain (non-sharded) eventloop reactor must not emit phantom
    serve_shard0_* metrics — a shard 0 with connections but zero
    submissions reads as a broken shard in an unsharded deployment."""
    from commefficient_tpu.obs import registry as obreg

    q = IngestQueue(capacity=8)
    t = EventLoopTransport(q, read_deadline_s=2.0)
    t.start()
    try:
        q.open_round(0, [1])
        before = obreg.default().snapshot().get("serve_shard0_conns")
        assert submit_over_socket(t.address, _sub(1)) == ACCEPTED
        time.sleep(0.1)
        after = obreg.default().snapshot().get("serve_shard0_conns")
        assert before == after  # untouched (absent, or a prior test's relic)
    finally:
        t.stop()
        q.shutdown()


def test_serve_max_conns_plumbs_through_config():
    s = _tiny_session()
    cfg = ServeConfig(quorum=3, transport="socket", payload="sketch",
                      socket_transport="eventloop", max_conns=7)
    svc = AggregationService(
        s, cfg, traffic=TrafficGenerator(
            TraceConfig(population=12, seed=5)))
    try:
        assert svc.transport.max_conns == 7
    finally:
        svc.close()


def test_eventloop_thread_hygiene():
    before = {th.name for th in __import__("threading").enumerate()}
    q = IngestQueue(capacity=8)
    t = EventLoopTransport(q, read_deadline_s=1.0)
    t.start()
    q.open_round(0, [1])
    submit_over_socket(t.address, _sub(1))
    t.stop()
    q.shutdown()
    time.sleep(0.1)
    after = {th.name for th in __import__("threading").enumerate()}
    assert not [n for n in after - before if n.startswith("serve-reactor")]


# --------------------------------------------------------- sharded ingest


def test_sharded_ingest_routes_and_counts():
    q = IngestQueue(capacity=64)
    tr = ShardedIngest(q, n_shards=2, read_deadline_s=2.0)
    tr.start()
    try:
        ids = list(range(40, 72))
        q.open_round(0, ids)
        for cid in ids:
            assert tr.submit(_sub(cid)) == ACCEPTED
        assert q.counters()["accepted"] == len(ids)
        counts = tr.counters()
        per_shard = [counts[str(k)]["submissions"] for k in range(2)]
        assert sum(per_shard) == len(ids)
        assert all(c > 0 for c in per_shard), per_shard
        assert all(counts[str(k)]["misrouted"] == 0 for k in range(2))
        # a misrouted submission is still decided correctly, but counted
        cid = ids[0]
        wrong = tr.shards[1 - shard_for(cid, 2)]
        assert submit_over_socket(wrong.address, _sub(cid)) == DUPLICATE
        counts = tr.counters()
        assert sum(counts[str(k)]["misrouted"] for k in range(2)) == 1
    finally:
        tr.stop()
        q.shutdown()


def test_sharded_shedding_hint_is_per_shard():
    """Per-shard SHEDDING: the shed reply carries a shard-load-scaled
    retry-after hint and the shard's own gauges move — an overloaded
    shard is distinguishable from an overloaded server."""
    q = IngestQueue(capacity=4, pending_capacity=0, shed_watermark=0.25,
                    shed_retry_after_s=1.0)
    tr = ShardedIngest(q, n_shards=2, read_deadline_s=2.0)
    tr.start()
    try:
        ids = list(range(8))
        q.open_round(0, ids)
        statuses = [tr.submit(_sub(cid)) for cid in ids]
        assert SHEDDING in statuses
        counts = tr.counters()
        shed_total = sum(counts[str(k)]["shed"] for k in range(2))
        assert shed_total >= 1
        hints = [counts[str(k)]["retry_after_s"] for k in range(2)
                 if counts[str(k)]["shed"]]
        assert all(h >= 1.0 for h in hints)
    finally:
        tr.stop()
        q.shutdown()


def test_shard_metrics_reach_prometheus_exposition():
    from commefficient_tpu.serve.metrics import render_prometheus

    q = IngestQueue(capacity=16)
    tr = ShardedIngest(q, n_shards=2, read_deadline_s=2.0)
    tr.start()
    try:
        q.open_round(0, [1, 2])
        tr.submit(_sub(1))
        body = render_prometheus()
        for k in range(2):
            assert f"serve_shard{k}_submissions_total" in body
            assert f"serve_shard{k}_retry_after_s" in body
    finally:
        tr.stop()
        q.shutdown()


def test_sharded_service_end_to_end_metrics():
    """A full served payload run over the sharded event-loop ingest: the
    rounds commit, and /metrics carries the shards block."""
    s = _tiny_session()
    cfg = ServeConfig(quorum=3, transport="socket", payload="sketch",
                      socket_transport="eventloop", shards=2,
                      metrics_port=0)
    svc = AggregationService(
        s, cfg, traffic=TrafficGenerator(
            TraceConfig(population=12, seed=5))).start()
    try:
        src = svc.source()
        for _ in range(2):
            prep = src.next()
            s.commit_round(s.dispatch_round(prep, LR))
            src.on_dispatched(s.round - 1)
            src.on_committed(s.round)
        src.stop()
        host, port = svc.metrics_server.address
        snap = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10).read())
        assert snap["transport_engine"] == "eventloop"
        assert set(snap["shards"]) == {"0", "1"}
        assert sum(snap["shards"][k]["submissions"]
                   for k in snap["shards"]) > 0
    finally:
        svc.close()
    assert s.round == 2


def test_shard_transport_config_validation():
    with pytest.raises(ValueError, match="n_shards must be >= 2"):
        ShardedIngest(IngestQueue(capacity=4), n_shards=1)
    s = _tiny_session()
    # socket_transport defaults to eventloop now — pin threaded explicitly
    # to keep exercising the shards-need-a-reactor rejection.
    with pytest.raises(ValueError, match="eventloop"):
        AggregationService(
            s, ServeConfig(quorum=3, transport="socket", payload="sketch",
                           socket_transport="threaded", shards=2),
            traffic=TrafficGenerator(TraceConfig(population=12)))
    with pytest.raises(ValueError, match="no connections to shard"):
        AggregationService(
            s, ServeConfig(quorum=3, transport="inproc", payload="sketch",
                           socket_transport="eventloop", shards=2),
            traffic=TrafficGenerator(TraceConfig(population=12)))


# ----------------------------------------------- CLI: flags + preempt/resume


@pytest.fixture
def tiny_cv(tmp_path, monkeypatch):
    import flax.linen as nn

    import commefficient_tpu.data.cifar as cifar_mod
    import cv_train

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=64, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)

    class _TinyNet(nn.Module):
        num_classes: int = 10
        dtype: str = "float32"

        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(8)(x))
            return nn.Dense(self.num_classes)(x)

    monkeypatch.setattr(cv_train, "ResNet9", _TinyNet)
    return tmp_path


_CLI_ARGV = [
    "--dataset", "cifar10", "--mode", "sketch", "--num_clients", "8",
    "--num_workers", "4", "--local_batch_size", "4", "--num_rounds", "4",
    "--k", "16", "--num_rows", "3", "--num_cols", "128", "--lr_scale",
    "0.05", "--weight_decay", "0", "--data_root", "/nonexistent",
    "--seed", "3", "--serve", "inproc", "--serve_payload", "sketch",
    "--serve_quorum", "3", "--serve_deadline", "2.0", "--serve_edges", "2",
]


@pytest.mark.chaos
def test_cli_edge_tree_preempt_resume_bit_identical(tiny_cv, tmp_path):
    """preempt -> exit 75 -> --resume mid-run THROUGH the edge-tree path
    == the uninterrupted edge-tree twin (params + requeue state) — the
    edge layer is round-scoped, so the committed-snapshot rewinds carry
    it for free, and this pins that they actually do."""
    import cv_train
    from commefficient_tpu.resilience import EXIT_RESUMABLE

    sa = cv_train.main(list(_CLI_ARGV))  # uninterrupted reference
    ckdir = str(tmp_path / "ck")
    chaos = ["--checkpoint_dir", ckdir, "--checkpoint_every", "1",
             "--fault_plan", "preempt@2"]
    with pytest.raises(SystemExit) as ei:
        cv_train.main(list(_CLI_ARGV) + chaos)
    assert ei.value.code == EXIT_RESUMABLE
    sc = cv_train.main(list(_CLI_ARGV) + chaos + ["--resume"])
    assert sc.round == 4
    _assert_params_equal(sa, sc)
    assert list(sa._requeue) == list(sc._requeue)


def test_cli_flag_validation(tiny_cv):
    import cv_train

    base = ["--dataset", "cifar10", "--mode", "sketch",
            "--data_root", "/nonexistent", "--num_rounds", "1"]
    with pytest.raises(SystemExit, match="one edge IS the flat merge"):
        cv_train.main(base + ["--serve", "inproc", "--serve_payload",
                              "sketch", "--serve_edges", "1"])
    with pytest.raises(SystemExit, match="serve_payload sketch"):
        cv_train.main(base + ["--serve", "inproc", "--serve_edges", "2"])
    with pytest.raises(SystemExit, match="serve socket"):
        cv_train.main(base + ["--serve", "inproc", "--serve_transport",
                              "eventloop", "--serve_shards", "2"])
    with pytest.raises(SystemExit, match="eventloop"):
        cv_train.main(base + ["--serve", "socket", "--serve_transport",
                              "threaded", "--serve_shards", "2"])
    with pytest.raises(SystemExit, match="does not compose"):
        cv_train.main(base + [
            "--serve", "inproc", "--serve_payload", "sketch",
            "--serve_edges", "2", "--serve_pipeline"])
    with pytest.raises(ValueError, match="edge_kill can never fire"):
        cv_train.main(base + ["--serve", "inproc",
                              "--fault_plan", "edge_kill@0:edges=0"])
