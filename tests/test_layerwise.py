"""Sketch-as-you-backprop (ISSUE 8 tentpole): layerwise Count-Sketch
accumulation — the dense [d] gradient never materializes — pinned
BIT-identical to the ravel path, plus the count-sketched server optimizer
state (--server_state sketch).

The bit-identity contract under test: `sketch_path="layerwise"` folds each
layer's gradient block into the running r x c table (sketch/layerwise.py)
instead of raveling the pytree into a flat [d] vector first, and produces
the IDENTICAL BITS — params, server mode state, and every logged metric —
across the fused, split, sharded (mesh == single-device reference), and
checkpoint+resume paths. The foundation is csvec._sketch_vec_rotation's
explicit slab-order left fold: per bucket both paths perform the same
ordered float sum (boundary slabs split across two leaves contribute an
exact ±0.0 from the non-owning leaf, which IEEE addition ignores).

conftest forces an 8-device CPU mesh, so the mesh tests run here and in
scripts/tier1_8dev.sh.

Known, deliberate non-bitwise caveat: the quarantine/dp_clip client NORMS
fold per-leaf partial sums (the flat path reduces one contiguous axis), so
the quarantine_median METRIC matches the ravel path at ~1e-6 relative, not
bitwise; the quarantine's behavior (rejected == dropped) is pinned bitwise
WITHIN the layerwise path below.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.flatten_util import ravel_pytree

from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated import engine
from commefficient_tpu.federated.api import FederatedSession
from commefficient_tpu.modes import modes
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.parallel import mesh as meshlib
from commefficient_tpu.sketch import csvec, layerwise


# --------------------------------------------------------------- unit layer


def _leaf_partition(flat, sizes, shapes=None):
    leaves, off = {}, 0
    for i, s in enumerate(sizes):
        leaf = flat[off:off + s]
        if shapes and shapes[i] is not None:
            leaf = leaf.reshape(shapes[i])
        leaves[f"l{i:02d}"] = jnp.asarray(leaf)
        off += s
    assert off == flat.size
    return leaves


@pytest.mark.parametrize("family", ["rotation", "random"])
@pytest.mark.parametrize("d,c,r,sizes", [
    (1000, 64, 3, (37, 200, 463, 300)),       # boundary slabs split mid-leaf
    (777, 1024, 5, (100, 677)),               # c > d: single slab
    (4096, 256, 3, (256, 1024, 2816)),        # slab-aligned leaves
])
def test_sketch_tree_bitwise_equals_sketch_vec(family, d, c, r, sizes):
    """THE unit pin: leaf-by-leaf accumulation == one-shot sketch of the
    raveled vector, bit for bit, for any leaf partition — multi-dim leaf
    shapes included (ravel order is row-major reshape)."""
    spec = csvec.CSVecSpec(d=d, c=c, r=r, seed=13, family=family)
    flat = np.random.RandomState(0).randn(d).astype(np.float32)
    shapes = [None] * len(sizes)
    if sizes[1] % 4 == 0:
        shapes[1] = (4, sizes[1] // 4)
    tree = _leaf_partition(flat, sizes, shapes)
    ref = jax.jit(lambda v: csvec.sketch_vec(spec, v))(jnp.asarray(flat))
    got = jax.jit(lambda t: layerwise.sketch_tree(spec, t))(tree)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_accumulate_leaf_single_block_matches_plan_path():
    spec = csvec.CSVecSpec(d=500, c=64, r=3, seed=5, family="rotation")
    flat = np.random.RandomState(1).randn(500).astype(np.float32)
    table = csvec.zero_table(spec)
    off = 0
    for s in (123, 250, 127):
        table = layerwise.accumulate_leaf(
            spec, table, jnp.asarray(flat[off:off + s]), off)
        off += s
    np.testing.assert_array_equal(
        np.asarray(csvec.sketch_vec(spec, jnp.asarray(flat))),
        np.asarray(table))


def test_apply_delta_tree_bitwise_equals_flat_apply():
    """Per-leaf sparse apply == flat scatter + unravel, bit for bit —
    idx = -1 padding and out-of-range entries contribute exactly nothing."""
    rs = np.random.RandomState(3)
    flat = rs.randn(600).astype(np.float32)
    tree = _leaf_partition(flat, (150, 250, 200), [None, (50, 5), None])
    pflat, unravel = ravel_pytree(tree)
    spec = csvec.CSVecSpec(d=600, c=128, r=3)
    idx = jnp.asarray(
        np.concatenate([rs.choice(600, size=20, replace=False),
                        [-1, -1, 650]]), jnp.int32)
    vals = jnp.asarray(rs.randn(23), jnp.float32)
    want = unravel(modes.apply_delta(pflat, {"idx": idx, "vals": vals}))
    got = layerwise.apply_delta_tree(tree, {"idx": idx, "vals": vals},
                                     spec=spec)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(want[k]), np.asarray(got[k]))
        assert want[k].shape == got[k].shape


def test_block_plan_and_config_validation():
    spec = csvec.CSVecSpec(d=100, c=32, r=3)
    with pytest.raises(ValueError, match="block plan covers"):
        layerwise.make_block_plan(spec, {"a": jnp.zeros(99)})
    mcfg = ModeConfig(mode="uncompressed", d=10, momentum_type="none",
                      error_type="none")
    with pytest.raises(ValueError, match="requires mode='sketch'"):
        engine.EngineConfig(mode=mcfg, sketch_path="layerwise")
    blocked = ModeConfig(mode="sketch", d=100, k=8, num_rows=3, num_cols=32,
                         hash_family="random", num_blocks=4)
    with pytest.raises(ValueError, match="num_blocks=1"):
        engine.EngineConfig(mode=blocked, sketch_path="layerwise")
    with pytest.raises(ValueError, match="sketch_path"):
        engine.EngineConfig(mode=blocked, sketch_path="bogus")


# ------------------------------------------------------------- engine layer


def init_mlp(key, din=10, dh=16, dout=4):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (din, dh)) * 0.1,
        "b1": jnp.zeros(dh),
        "w2": jax.random.normal(k2, (dh, dout)) * 0.1,
        "b2": jnp.zeros(dout),
    }


def mlp_loss(params, net_state, batch, rng):
    h = jnp.tanh(batch["x"] @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    logp = jax.nn.log_softmax(logits)
    per_ex = -jnp.take_along_axis(logp, batch["y"][:, None], axis=1)[:, 0]
    mask = batch["mask"]
    loss = (per_ex * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return loss, {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()},
    }


def _batch(key, W=8, n=4, din=10, dout=4):
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (W * n, din))
    w_true = jax.random.normal(kw, (din, dout))
    data = {"x": x, "y": (x @ w_true).argmax(-1), "mask": jnp.ones(W * n)}
    return jax.tree.map(lambda a: a.reshape((W, n) + a.shape[1:]), data)


SKETCH_KW = dict(mode="sketch", k=16, num_rows=3, num_cols=1024,
                 hash_family="rotation", momentum_type="virtual",
                 error_type="virtual")

ENGINE_CASES = [
    ("plain", {}),
    ("dropout_guard", dict(client_dropout=0.25, on_nonfinite="skip")),
    ("chunked", dict(client_chunk=2)),
    ("random_family", {}),  # hash_family overridden below
]


def _cfg(eng_kw, sketch_path, family="rotation", shards=1):
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(**{**SKETCH_KW, "d": d, "hash_family": family})
    kw = dict(eng_kw)
    if shards > 1:
        kw["client_shards"] = shards
    return params, engine.EngineConfig(mode=mcfg, weight_decay=5e-4,
                                       sketch_path=sketch_path, **kw)


def _run_steps(make, params, cfg, rounds=3, W=8):
    step = jax.jit(make(cfg))
    state = engine.init_server_state(
        cfg, jax.tree.map(jnp.copy, params), {})
    out = []
    for i in range(rounds):
        b = dict(_batch(jax.random.PRNGKey(10 + i), W=W))
        b[engine.VALID_KEY] = jnp.ones(W)
        state, _, m = step(state, b, {}, jnp.float32(0.1),
                           jax.random.PRNGKey(100 + i))
        out.append(jax.device_get(m))
    return state, out


def _assert_bitwise(a, b, mode_state=True):
    sa, ma = a
    sb, mb = b
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(sa["params"])[0]),
        np.asarray(ravel_pytree(sb["params"])[0]))
    if mode_state:
        for k in ("Vvelocity", "Verror"):
            np.testing.assert_array_equal(
                np.asarray(sa["mode_state"][k]),
                np.asarray(sb["mode_state"][k]))
    for ra, rb in zip(ma, mb):
        assert set(ra) == set(rb)
        for k in ra:
            np.testing.assert_array_equal(np.asarray(ra[k]),
                                          np.asarray(rb[k]), err_msg=k)


@pytest.mark.parametrize("name, eng_kw", ENGINE_CASES,
                         ids=[c[0] for c in ENGINE_CASES])
def test_layerwise_fused_bit_identical_to_ravel(name, eng_kw):
    """THE acceptance pin (fused): the layerwise round — per-leaf reduce,
    table accumulation, per-leaf delta apply — produces the identical bits
    (params, server sketch state, every metric) as the ravel round, across
    dropout/nonfinite-guard/client_chunk configs and both hash families."""
    family = "random" if name == "random_family" else "rotation"
    params, cfg_r = _cfg(eng_kw, "ravel", family)
    _, cfg_l = _cfg(eng_kw, "layerwise", family)
    ref = _run_steps(lambda c: engine.make_round_step(mlp_loss, c),
                     params, cfg_r)
    got = _run_steps(lambda c: engine.make_round_step(mlp_loss, c),
                     params, cfg_l)
    _assert_bitwise(ref, got)


def test_layerwise_split_bit_identical_to_ravel_and_fused():
    params, cfg_r = _cfg({}, "ravel")
    _, cfg_l = _cfg({}, "layerwise")
    split = lambda c: engine.compose_split(  # noqa: E731
        *engine.make_split_round_step(mlp_loss, c))
    ref_split = _run_steps(split, params, cfg_r)
    lw_split = _run_steps(split, params, cfg_l)
    lw_fused = _run_steps(lambda c: engine.make_round_step(mlp_loss, c),
                          params, cfg_l)
    _assert_bitwise(ref_split, lw_split)
    _assert_bitwise(lw_split, lw_fused)


def test_layerwise_sharded_bit_identical_to_ravel():
    """Sharded acceptance: on the 8-device mesh the layerwise round ==
    the ravel round bit-for-bit (same program shape, same ordered table
    merge — only the accumulation differs), and the mesh == single-device
    layerwise reference holds to the same contract the ravel path pins
    (params + metrics bitwise; server tables to last-bit tolerance,
    the documented XLA:CPU while-body-vs-inlined fp difference)."""
    mesh = meshlib.make_mesh(8)
    params, cfg_r = _cfg(dict(client_dropout=0.25, on_nonfinite="skip"),
                         "ravel", shards=8)
    _, cfg_l = _cfg(dict(client_dropout=0.25, on_nonfinite="skip"),
                    "layerwise", shards=8)
    W = 16
    mesh_r = _run_steps(
        lambda c: engine.make_sharded_round_step(mlp_loss, c, mesh),
        params, cfg_r, W=W)
    mesh_l = _run_steps(
        lambda c: engine.make_sharded_round_step(mlp_loss, c, mesh),
        params, cfg_l, W=W)
    _assert_bitwise(mesh_r, mesh_l)
    ref_l = _run_steps(
        lambda c: engine.make_sharded_round_step(mlp_loss, c, None),
        params, cfg_l, W=W)
    _assert_bitwise(ref_l, mesh_l, mode_state=False)
    for k in ("Vvelocity", "Verror"):
        np.testing.assert_allclose(
            np.asarray(ref_l[0]["mode_state"][k]),
            np.asarray(mesh_l[0]["mode_state"][k]), rtol=0, atol=1e-7)


def test_layerwise_sharded_split_bit_identical_to_sharded_fused():
    """The sharded split pair (table crosses the program boundary instead
    of a [S, d] dense stack) == the sharded fused layerwise program, and
    == the ravel sharded split, all on the same mesh."""
    mesh = meshlib.make_mesh(8)
    params, cfg_l = _cfg({}, "layerwise", shards=8)
    _, cfg_r = _cfg({}, "ravel", shards=8)
    split = lambda c: engine.compose_split(  # noqa: E731
        *engine.make_sharded_split_round_step(mlp_loss, c, mesh))
    lw_split = _run_steps(split, params, cfg_l, W=16)
    rv_split = _run_steps(split, params, cfg_r, W=16)
    lw_fused = _run_steps(
        lambda c: engine.make_sharded_round_step(mlp_loss, c, mesh),
        params, cfg_l, W=16)
    _assert_bitwise(rv_split, lw_split)
    _assert_bitwise(lw_fused, lw_split)


def test_layerwise_dead_client_nan_inert():
    """_valid masking on the layerwise path: a dead client's row may carry
    NaN garbage and still contribute exact zero — the round equals the one
    whose dead rows are zeros, bit for bit (mask_rows per leaf)."""
    params, cfg = _cfg({}, "layerwise")
    step = jax.jit(engine.make_round_step(mlp_loss, cfg))
    W = 8
    valid = np.ones(W, np.float32)
    valid[2] = 0.0
    valid[5] = 0.0

    def run(poison):
        b = dict(_batch(jax.random.PRNGKey(42), W=W))
        if poison:
            x = np.asarray(b["x"]).copy()
            x[2] = np.nan
            x[5] = np.inf
            b["x"] = jnp.asarray(x)
        else:
            x = np.asarray(b["x"]).copy()
            x[2] = 0.0
            x[5] = 0.0
            b["x"] = jnp.asarray(x)
        b[engine.VALID_KEY] = jnp.asarray(valid)
        state = engine.init_server_state(
            cfg, jax.tree.map(jnp.copy, params), {})
        state, _, m = step(state, b, {}, jnp.float32(0.1),
                           jax.random.PRNGKey(0))
        return state, [jax.device_get(m)]

    _assert_bitwise(run(poison=True), run(poison=False))


def test_layerwise_quarantine_rejected_equals_dropped():
    """Quarantine on the layerwise path: a poisoned client rejected by the
    update-norm screen == the same client dropped via the validity mask,
    bit for bit (round 2, once the running median is seeded). Cross-path:
    the quarantine_median metric matches ravel at tolerance only (per-leaf
    norm fold — the documented caveat)."""
    eng_kw = dict(client_update_clip=3.0)
    params, cfg = _cfg(eng_kw, "layerwise")
    step = jax.jit(engine.make_round_step(mlp_loss, cfg))
    W = 8

    def run(poison_pos=None, drop_pos=None):
        state = engine.init_server_state(
            cfg, jax.tree.map(jnp.copy, params), {})
        ms = []
        for i in range(3):
            b = dict(_batch(jax.random.PRNGKey(10 + i), W=W))
            b[engine.VALID_KEY] = jnp.ones(W)
            if i == 2 and poison_pos is not None:
                x = np.asarray(b["x"]).copy()
                x[poison_pos] = np.nan  # non-finite norm -> quarantined
                b["x"] = jnp.asarray(x)
            if i == 2 and drop_pos is not None:
                v = np.ones(W, np.float32)
                v[drop_pos] = 0.0
                b[engine.VALID_KEY] = jnp.asarray(v)
            state, _, m = step(state, b, {}, jnp.float32(0.1),
                               jax.random.PRNGKey(100 + i))
            ms.append(jax.device_get(m))
        return state, ms

    quarantined = run(poison_pos=3)
    dropped = run(drop_pos=3)
    assert quarantined[1][2]["clients_quarantined"] == 1.0
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(quarantined[0]["params"])[0]),
        np.asarray(ravel_pytree(dropped[0]["params"])[0]))

    _, cfg_r = _cfg(eng_kw, "ravel")
    step_r = jax.jit(engine.make_round_step(mlp_loss, cfg_r))
    sr = engine.init_server_state(cfg_r, jax.tree.map(jnp.copy, params), {})
    b = dict(_batch(jax.random.PRNGKey(10), W=W))
    b[engine.VALID_KEY] = jnp.ones(W)
    _, _, mr = step_r(sr, b, {}, jnp.float32(0.1), jax.random.PRNGKey(100))
    np.testing.assert_allclose(
        float(quarantined[1][0]["quarantine_median"]),
        float(jax.device_get(mr)["quarantine_median"]), rtol=1e-5)


# ------------------------------------------------------------ session layer


def _mlp_dataset(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.normal(size=(n, 10)).astype(np.float32)
    y = rng.randint(0, 4, size=n).astype(np.int32)
    return FedDataset(x, y, shard_iid(n, 16, np.random.RandomState(1)))


def _session(sketch_path="ravel", mesh=None, client_shards=0, split=False,
             **kw):
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    return FederatedSession(
        train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss,
        params=jax.tree.map(jnp.copy, params), net_state={},
        mode_cfg=ModeConfig(**{**SKETCH_KW, "d": d}),
        train_set=_mlp_dataset(), num_workers=8, local_batch_size=2,
        seed=7, mesh=mesh, client_shards=client_shards, split_compile=split,
        sketch_path=sketch_path, **kw,
    )


def test_layerwise_session_bit_identical_to_ravel_session():
    """Session-level acceptance: run_round + the run_rounds fused K-round
    block on a layerwise session == the ravel session, bit for bit —
    params and EVERY logged metric row (comm accounting included)."""
    a = _session("ravel")
    b = _session("layerwise")
    seq_a = [a.run_round(0.1), a.run_round(0.2)] + a.run_rounds([0.05, 0.1])
    seq_b = [b.run_round(0.1), b.run_round(0.2)] + b.run_rounds([0.05, 0.1])
    for ma, mb in zip(seq_a, seq_b):
        assert ma == mb
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(a.state["params"])[0]),
        np.asarray(ravel_pytree(b.state["params"])[0]))
    assert a.comm_mb_total == b.comm_mb_total


def test_layerwise_session_mesh_and_split():
    """Layerwise over the 8-way mesh session == ravel over the same mesh,
    and the split-compile layerwise mesh session matches both — every row
    and the params bitwise."""
    a = _session("ravel", mesh=meshlib.make_mesh(8))
    b = _session("layerwise", mesh=meshlib.make_mesh(8))
    c = _session("layerwise", mesh=meshlib.make_mesh(8), split=True)
    for _ in range(2):
        ma, mb, mc = a.run_round(0.1), b.run_round(0.1), c.run_round(0.1)
        assert ma == mb == mc
    pa = np.asarray(ravel_pytree(a.state["params"])[0])
    np.testing.assert_array_equal(
        pa, np.asarray(ravel_pytree(b.state["params"])[0]))
    np.testing.assert_array_equal(
        pa, np.asarray(ravel_pytree(c.state["params"])[0]))


def test_layerwise_checkpoint_resume_bit_identical(tmp_path):
    """Checkpoint+resume mid-run ON THE LAYERWISE PATH: 2 rounds, save,
    fresh layerwise session restores, 2 more rounds — bit-identical to 4
    uninterrupted rounds AND to the same schedule on the ravel path."""
    from commefficient_tpu.utils import checkpoint as ckpt

    lrs = [0.1, 0.2, 0.05, 0.1]
    a = _session("layerwise", donate_state=False)
    straight = [a.run_round(lr) for lr in lrs]

    b = _session("layerwise", donate_state=False)
    first = [b.run_round(lr) for lr in lrs[:2]]
    ckpt.save(str(tmp_path / "ck"), b)

    c = _session("layerwise", donate_state=False)
    assert ckpt.restore_latest(str(tmp_path / "ck"), c)
    assert c.round == 2
    resumed = first + [c.run_round(lr) for lr in lrs[2:]]
    for ma, mb in zip(straight, resumed):
        assert ma == mb
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(a.state["params"])[0]),
        np.asarray(ravel_pytree(c.state["params"])[0]))

    r = _session("ravel", donate_state=False)
    for lr in lrs:
        r.run_round(lr)
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(r.state["params"])[0]),
        np.asarray(ravel_pytree(c.state["params"])[0]))


# ----------------------------------------- count-sketched server optimizer


def test_sketched_momentum_bitwise_at_lossless_width():
    """--server_state sketch parity pin: with c >= d (rotation family) the
    table is a signed permutation — no collisions, exact estimates — so
    true_topk with sketch-resident momentum/error produces the IDENTICAL
    bits (params + metrics) as the dense default, round after round; the
    server state itself shrinks from 2*[d] to 2*[r, c]."""
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    base = ModeConfig(mode="true_topk", d=d, k=24, momentum_type="virtual",
                      error_type="virtual")
    c_lossless = 1 << (d - 1).bit_length()  # next pow2 >= d
    sk = dataclasses.replace(base, server_state="sketch", num_rows=3,
                             num_cols=c_lossless, hash_family="rotation")
    assert modes.init_server_state(sk)["Vvelocity"].shape == (3, c_lossless)
    assert modes.init_server_state(base)["Vvelocity"].shape == (d,)

    def run(mcfg):
        cfg = engine.EngineConfig(mode=mcfg, weight_decay=5e-4)
        return _run_steps(lambda c: engine.make_round_step(mlp_loss, c),
                          params, cfg, rounds=4)

    (s_dense, m_dense), (s_sk, m_sk) = run(base), run(sk)
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(s_dense["params"])[0]),
        np.asarray(ravel_pytree(s_sk["params"])[0]))
    for ra, rb in zip(m_dense, m_sk):
        for k in ra:
            np.testing.assert_array_equal(np.asarray(ra[k]),
                                          np.asarray(rb[k]), err_msg=k)


def test_sketched_momentum_compressed_width_runs():
    """c < d: the FetchSGD-style approximation — still converging table
    arithmetic, finite state, r x c memory; local_topk's virtual-error
    variant rides the same branch."""
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    for mode, extra in (("true_topk", {}),
                        ("local_topk", dict(error_type="virtual",
                                            momentum_type="virtual"))):
        mcfg = ModeConfig(**{**dict(mode=mode, d=d, k=16,
                                    momentum_type="virtual",
                                    error_type="virtual",
                                    server_state="sketch", num_rows=3,
                                    num_cols=128), **extra})
        cfg = engine.EngineConfig(mode=mcfg)
        state, ms = _run_steps(
            lambda c: engine.make_round_step(mlp_loss, c), params, cfg,
            rounds=2)
        assert state["mode_state"]["Vvelocity"].shape == (3, 128)
        assert np.isfinite(
            np.asarray(ravel_pytree(state["params"])[0])).all()
        assert all(np.isfinite(list(m.values())).all() for m in ms)


def test_server_state_validation():
    with pytest.raises(ValueError, match="top-k release"):
        ModeConfig(mode="uncompressed", d=10, server_state="sketch",
                   momentum_type="virtual", error_type="none")
    with pytest.raises(ValueError, match="error_type='virtual'"):
        ModeConfig(mode="local_topk", d=10, k=4, server_state="sketch",
                   momentum_type="virtual", error_type="local",
                   num_cols=32)
    with pytest.raises(ValueError, match="num_cols"):
        ModeConfig(mode="true_topk", d=10, k=4, server_state="sketch",
                   momentum_type="virtual", error_type="virtual")
    # mode=sketch is already sketch-state: both spellings are accepted
    for ss in ("dense", "sketch"):
        ModeConfig(mode="sketch", d=10, k=4, num_cols=32, server_state=ss)
