"""Expert-parallel MoE tests: the dispatch/combine einsum path must match
the dense oracle when capacity is not binding, degrade to pass-through on
overflow, and run sharded over an 'expert' mesh axis with identical
results."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from commefficient_tpu.ops import moe

E, D, H = 8, 16, 32


def _expert_fn(p, h):
    return jnp.tanh(h @ p["wi"]) @ p["wo"]


def _params(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return (
        0.3 * jax.random.normal(k1, (D, E)),  # router
        {
            "wi": 0.3 * jax.random.normal(k2, (E, D, H)),
            "wo": 0.3 * jax.random.normal(k3, (E, H, D)),
        },
    )


def test_moe_matches_dense_oracle_when_capacity_ample():
    router, experts = _params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (64, D))
    # capacity_factor = E guarantees C >= T, so nothing is ever dropped
    y, aux = moe.moe_ffn(x, router, experts, _expert_fn, capacity_factor=float(E))
    want = moe.dense_oracle(x, router, experts, _expert_fn)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=1e-5, atol=1e-5)
    assert float(aux) > 0.0


def test_moe_overflow_passes_through():
    """capacity 1 token/expert: dropped tokens keep x (identity), kept ones
    get gate * expert_out + (1-gate) * x."""
    router, experts = _params(jax.random.PRNGKey(2))
    x = jax.random.normal(jax.random.PRNGKey(3), (64, D))
    y, _ = moe.moe_ffn(x, router, experts, _expert_fn, capacity_factor=E / 64.0)
    # with C = 1, at most E tokens are routed; everyone else is identity
    changed = (np.abs(np.asarray(y - x)) > 1e-6).any(axis=1).sum()
    assert changed <= E
    assert changed > 0


def test_moe_sharded_over_expert_axis_matches():
    mesh = Mesh(np.array(jax.devices()[:8]), ("expert",))
    router, experts = _params(jax.random.PRNGKey(4))
    x = jax.random.normal(jax.random.PRNGKey(5), (64, D))
    ref, aux_ref = jax.jit(
        lambda x, r, e: moe.moe_ffn(x, r, e, _expert_fn, capacity_factor=2.0)
    )(x, router, experts)

    experts_sharded = jax.device_put(experts, NamedSharding(mesh, P("expert")))
    x_repl = jax.device_put(x, NamedSharding(mesh, P()))
    got, aux = jax.jit(
        lambda x, r, e: moe.moe_ffn(x, r, e, _expert_fn, capacity_factor=2.0)
    )(x_repl, router, experts_sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)


def test_moe_grads_flow_to_router_and_experts():
    router, experts = _params(jax.random.PRNGKey(6))
    x = jax.random.normal(jax.random.PRNGKey(7), (32, D))

    def loss(r, e):
        y, aux = moe.moe_ffn(x, r, e, _expert_fn, capacity_factor=2.0)
        return jnp.mean(y**2) + 0.01 * aux

    gr, ge = jax.grad(loss, argnums=(0, 1))(router, experts)
    assert float(jnp.abs(gr).sum()) > 0
    assert all(float(jnp.abs(g).sum()) > 0 for g in jax.tree.leaves(ge))


def test_moe_gpt2_trains_federated():
    """GPT-2 with MoE blocks (cfg.moe_experts) trains through the federated
    engine: loss falls and the sown load-balancing aux reaches the metrics."""
    import dataclasses

    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.federated import engine
    from commefficient_tpu.models.gpt2 import TINY, GPT2LMHead
    from commefficient_tpu.models.losses import make_lm_loss
    from commefficient_tpu.modes.config import ModeConfig

    T = 32
    cfg = dataclasses.replace(TINY, n_positions=T, moe_experts=4)
    model = GPT2LMHead(cfg)
    ids0 = jnp.zeros((1, T), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids0, train=False)["params"]
    assert "moe_mlp" in params["h_1"] and "mlp" in params["h_0"]  # every 2nd
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(mode="uncompressed", d=d, momentum_type="virtual", error_type="none")
    ecfg = engine.EngineConfig(mode=mcfg)
    state = engine.init_server_state(ecfg, params, {})
    loss_fn = make_lm_loss(model, train=True, moe_aux_coef=0.01)
    step = jax.jit(engine.make_round_step(loss_fn, ecfg))

    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 2, T), 0, cfg.vocab_size)
    batch = {"input_ids": ids, "labels": ids, "mask": jnp.ones((4, 2, T))}
    first, best = None, float("inf")
    for rnd in range(14):
        state, _, m = step(state, batch, {}, jnp.float32(0.1), jax.random.PRNGKey(rnd))
        nll = float(m["loss_sum"]) / float(m["count"])
        first = nll if first is None else first
        best = min(best, nll)
        # sum/count pair: the engine sums metrics over the W=4 clients
        assert float(m["moe_aux_sum"]) > 0.0
        assert float(m["moe_aux_count"]) == 4.0
    assert best < first * 0.9, (first, best)


def test_moe_checkpoint_roundtrip(tmp_path):
    """MoE params (router/wi/wo under moe_mlp) survive the orbax
    checkpoint/restore path bit-for-bit via the standard session flow."""
    import dataclasses

    import gpt2_train
    from commefficient_tpu.utils import checkpoint as ckpt
    from commefficient_tpu.utils.config import make_parser, resolve_defaults
    from jax.flatten_util import ravel_pytree

    argv = [
        "--model_size", "tiny", "--num_clients", "10", "--num_workers", "2",
        "--mode", "uncompressed", "--moe_experts", "4", "--seq_len", "32",
        "--local_batch_size", "2", "--data_root", "/nonexistent",
        "--checkpoint_dir", str(tmp_path),
    ]
    args = resolve_defaults(make_parser("gpt2").parse_args(argv))
    session = gpt2_train.build(args)[0]
    for _ in range(2):
        session.run_round(0.05)
    ckpt.save(str(tmp_path), session)
    want = np.asarray(ravel_pytree(session.state["params"])[0])

    session2 = gpt2_train.build(args)[0]
    ckpt.restore(ckpt.latest(str(tmp_path)), session2)
    got = np.asarray(ravel_pytree(session2.state["params"])[0])
    np.testing.assert_array_equal(got, want)
    assert session2.round == 2
