"""Test config: force an 8-device CPU mesh so multi-device sharding paths run
without TPU hardware (SURVEY.md §4 "Distributed without a cluster")."""

import os

# Must be set before jax initialises its backends. Append (don't setdefault):
# a pre-existing XLA_FLAGS must not silently drop the forced 8-device mesh.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
