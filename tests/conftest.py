"""Test config: force an 8-device CPU mesh so multi-device sharding paths run
without TPU hardware (SURVEY.md §4 "Distributed without a cluster"). The
hermetic dance (axon-plugin strip + platform pin) lives in
commefficient_tpu.utils.hermetic, shared with bench.py and __graft_entry__."""

import os

from commefficient_tpu.utils.hermetic import force_hermetic_cpu

force_hermetic_cpu(8)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# Persistent XLA compile cache: OPT-IN only. A repo-local default cache
# sounded right for this compile-bound suite, but on this box executables
# RELOADED from the disk cache are broken — the same jitted step that
# passes cold returns all-NaN params or segfaults the interpreter when a
# second process deserializes the cached executable (reproduced on
# tests/test_checkpoint.py: cold run passes, warm-cache rerun dies). That
# single poisoned default took the whole tier-1 suite from 184 passing to
# 0 (the segfault kills pytest mid-run). Export JAX_COMPILATION_CACHE_DIR
# explicitly if your jaxlib's cache round-trips correctly.
import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    # the env var alone is latched by jax._src.config at ITS import time,
    # which on this box happens in sitecustomize (axon plugin registration)
    # before conftest runs — in-process tests need the explicit update;
    # subprocess CLI tests inherit the env var
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )


def hermetic_subprocess_env() -> dict:
    """Env for SUBPROCESS tests: strip the axon plugin trigger and pin the
    8-device CPU mesh — the one shared copy of the dance (also used by
    test_distributed / test_determinism; in-process tests are already
    hermetic via force_hermetic_cpu above)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return env
