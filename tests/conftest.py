"""Test config: force an 8-device CPU mesh so multi-device sharding paths run
without TPU hardware (SURVEY.md §4 "Distributed without a cluster"). The
hermetic dance (axon-plugin strip + platform pin) lives in
commefficient_tpu.utils.hermetic, shared with bench.py and __graft_entry__."""

from commefficient_tpu.utils.hermetic import force_hermetic_cpu

force_hermetic_cpu(8)

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)


def hermetic_subprocess_env() -> dict:
    """Env for SUBPROCESS tests: strip the axon plugin trigger and pin the
    8-device CPU mesh — the one shared copy of the dance (also used by
    test_distributed / test_determinism; in-process tests are already
    hermetic via force_hermetic_cpu above)."""
    import os

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return env


def repo_root() -> str:
    import os

    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
