"""Test config: force an 8-device CPU mesh so multi-device sharding paths run
without TPU hardware (SURVEY.md §4 "Distributed without a cluster")."""

import os

# Must be set before jax initialises its backends. Append (don't setdefault):
# a pre-existing XLA_FLAGS must not silently drop the forced 8-device mesh.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# This machine's sitecustomize registers a TPU-tunnel PJRT plugin ("axon") in
# every interpreter; its backend init can hang when the tunnel is down, even
# under JAX_PLATFORMS=cpu. Tests must be hermetic on the CPU mesh, so drop the
# factory before any backend is initialised.
from jax._src import xla_bridge  # noqa: E402

xla_bridge._backend_factories.pop("axon", None)

# A pytest plugin may import jax before this conftest, in which case jax has
# already latched JAX_PLATFORMS from the ambient env ("axon"); set the config
# explicitly rather than relying on the env write above.
jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_threefry_partitionable", True)
