"""Test config: force an 8-device CPU mesh so multi-device sharding paths run
without TPU hardware (SURVEY.md §4 "Distributed without a cluster"). The
hermetic dance (axon-plugin strip + platform pin) lives in
commefficient_tpu.utils.hermetic, shared with bench.py and __graft_entry__."""

from commefficient_tpu.utils.hermetic import force_hermetic_cpu

force_hermetic_cpu(8)

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
