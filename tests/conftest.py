"""Test config: force an 8-device CPU mesh so multi-device sharding paths run
without TPU hardware (SURVEY.md §4 "Distributed without a cluster"). The
hermetic dance (axon-plugin strip + platform pin) lives in
commefficient_tpu.utils.hermetic, shared with bench.py and __graft_entry__."""

import os

from commefficient_tpu.utils.hermetic import force_hermetic_cpu

force_hermetic_cpu(8)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# Persistent XLA compile cache for the compile-bound suite on this 1-core
# box. Two hooks are BOTH required: the env var alone is latched by
# jax._src.config at ITS import time, which on this box happens in
# sitecustomize (axon plugin registration) before conftest runs — so the
# in-process suite needs the explicit config.update below, while subprocess
# CLI tests (fresh interpreters) pick the cache up from the inherited env
# var. Opt out with JAX_COMPILATION_CACHE_DIR="" (empty disables).
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR", os.path.join(repo_root(), ".jax_cache")
)
if not os.environ["JAX_COMPILATION_CACHE_DIR"]:
    del os.environ["JAX_COMPILATION_CACHE_DIR"]

import jax  # noqa: E402

jax.config.update("jax_threefry_partitionable", True)
if "JAX_COMPILATION_CACHE_DIR" in os.environ:
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )


def hermetic_subprocess_env() -> dict:
    """Env for SUBPROCESS tests: strip the axon plugin trigger and pin the
    8-device CPU mesh — the one shared copy of the dance (also used by
    test_distributed / test_determinism; in-process tests are already
    hermetic via force_hermetic_cpu above)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return env
