"""Per-client persistent state at scale (SURVEY.md §7 hard part (b)): the
[num_clients, d] local_topk error state sharded over the mesh client axis —
parity with the unsharded session, padding for non-divisible client counts,
and the measured (not worst-case) down-link accounting."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P

from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated.api import FederatedSession
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.parallel import mesh as meshlib
from commefficient_tpu.utils.comm import BYTES_PAIR


def _mlp_loss(din, dh, dout):
    def loss_fn(params, net_state, batch, rng):
        h = jnp.tanh(batch["x"] @ params["w1"] + params["b1"])
        logits = h @ params["w2"] + params["b2"]
        logp = jax.nn.log_softmax(logits)
        per_ex = -jnp.take_along_axis(logp, batch["y"][:, None], axis=1)[:, 0]
        mask = batch["mask"]
        loss = (per_ex * mask).sum() / jnp.maximum(mask.sum(), 1.0)
        return loss, {"net_state": net_state,
                      "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()}}

    return loss_fn


def _init_mlp(key, din, dh, dout):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (din, dh)) * 0.1,
        "b1": jnp.zeros(dh),
        "w2": jax.random.normal(k2, (dh, dout)) * 0.1,
        "b2": jnp.zeros(dout),
    }


def _dataset(num_clients, per_client, din, dout, seed=0):
    rng = np.random.RandomState(seed)
    n = num_clients * per_client
    x = rng.normal(size=(n, din)).astype(np.float32)
    w = rng.normal(size=(din, dout)).astype(np.float32)
    y = (x @ w).argmax(-1).astype(np.int32)
    return FedDataset(x, y, shard_iid(n, num_clients, rng))


def _session(num_clients, din=10, dh=16, dout=4, mesh=None, seed=3, k=8,
             num_workers=8):
    params = _init_mlp(jax.random.PRNGKey(0), din, dh, dout)
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(mode="local_topk", d=d, k=k, momentum_type="none",
                      error_type="local", num_clients=num_clients)
    return FederatedSession(
        train_loss_fn=_mlp_loss(din, dh, dout),
        eval_loss_fn=_mlp_loss(din, dh, dout),
        params=params, net_state={}, mode_cfg=mcfg,
        train_set=_dataset(num_clients, 4, din, dout),
        num_workers=num_workers, local_batch_size=4, seed=seed, mesh=mesh,
    )


def test_mesh_mismatch_rounds_cohort_to_shardable_size():
    """num_workers=12 on the 8-way client mesh: instead of the old silent
    single-device fallback (an 8x slowdown on a pod — VERDICT r3 weak #4),
    the cohort rounds UP to 16 and the round stays sharded."""
    mesh = meshlib.make_mesh(8)
    s = _session(32, mesh=mesh, num_workers=12)
    assert s.num_workers == 16
    assert s.mesh is not None
    m = s.run_round(0.1)
    assert np.isfinite(m["loss_sum"])


def test_mesh_mismatch_rounds_down_when_capped_by_clients():
    """Rounding up would exceed the client count (20 clients, want 16 -> up
    is 24 > 20): use the largest shardable cohort instead (16)."""
    mesh = meshlib.make_mesh(8)
    s = _session(20, mesh=mesh, num_workers=17)
    assert s.num_workers == 16
    assert s.mesh is not None


def test_mesh_mismatch_raises_when_unshardable():
    """Fewer clients than mesh shards: no viable cohort exists — must raise
    with the fix spelled out, never silently unshard."""
    import pytest

    mesh = meshlib.make_mesh(8)
    with pytest.raises(ValueError, match="num_devices"):
        _session(4, mesh=mesh, num_workers=4)


def test_cv_train_path_rounds_cohort(monkeypatch, tmp_path):
    """The cv_train build path (paper config #2 uses --num_workers 100, which
    8 devices don't divide) must come out sharded with a rounded cohort."""
    import cv_train
    from commefficient_tpu.utils.config import make_parser, resolve_defaults
    import commefficient_tpu.data.cifar as cifar_mod

    orig = cifar_mod.load_cifar_fed

    def tiny(*a, **kw):
        kw.update(synthetic_train=256, synthetic_test=32)
        return orig(*a, **kw)

    monkeypatch.setattr(cv_train, "load_cifar_fed", tiny)
    args = resolve_defaults(make_parser("cv").parse_args([
        "--dataset", "cifar10", "--mode", "uncompressed", "--num_clients", "128",
        "--num_workers", "100", "--local_batch_size", "2",
        "--data_root", "/nonexistent",
    ]))
    session, _ = cv_train.build(args)
    assert session.num_workers == 104  # rounded up from 100 to a multiple of 8
    assert session.mesh is not None


def test_sharded_client_state_matches_unsharded():
    """Same seeds -> same sampled clients -> identical params and client
    error state whether the [num_clients, d] state lives sharded on the mesh
    or replicated on one device."""
    mesh = meshlib.make_mesh(8)
    s_ref = _session(16, mesh=None)
    s_mesh = _session(16, mesh=mesh)
    for _ in range(3):
        m_ref = s_ref.run_round(0.1)
        m_mesh = s_mesh.run_round(0.1)
        assert m_ref["loss_sum"] == float(np.float32(m_mesh["loss_sum"])) or np.isclose(
            m_ref["loss_sum"], m_mesh["loss_sum"], rtol=1e-5
        )
    np.testing.assert_allclose(
        np.asarray(ravel_pytree(s_ref.state["params"])[0]),
        np.asarray(ravel_pytree(s_mesh.state["params"])[0]),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(s_ref.client_state["error"]),
        np.asarray(s_mesh.client_state["error"]),
        rtol=1e-5, atol=1e-6,
    )


def test_client_state_sharding_and_padding_at_scale():
    """num_clients=1027 (non-divisible), d ~ 1e5: state is padded to 1032 and
    its client axis sharded over the 8-device mesh; rounds run and only
    sampled clients' rows change."""
    mesh = meshlib.make_mesh(8)
    s = _session(1027, din=100, dh=900, dout=4, mesh=mesh, k=64)
    err = s.client_state["error"]
    assert err.shape[0] == 1032  # padded to a multiple of 8
    assert err.sharding.spec == P(meshlib.CLIENT_AXIS)
    # per-device shard holds 1/8 of the rows
    assert err.addressable_shards[0].data.shape[0] == 1032 // 8
    m = s.run_round(0.1)
    assert np.isfinite(m["loss_sum"])
    touched = np.unique(np.nonzero(np.asarray(s.client_state["error"]))[0])
    assert 1 <= len(touched) <= 8  # exactly the sampled cohort (or fewer)
    assert touched.max() < 1027  # padding rows never written


def test_checkpoint_portable_between_mesh_and_unsharded(tmp_path):
    """A checkpoint saved by a mesh session (padded, sharded client state)
    restores into an unsharded session and vice versa — padding is stripped
    at save and re-applied per the restoring session's mesh."""
    from commefficient_tpu.utils import checkpoint as ckpt

    mesh = meshlib.make_mesh(8)
    s_mesh = _session(12, mesh=mesh, seed=5)  # pads 12 -> 16
    for _ in range(2):
        s_mesh.run_round(0.1)
    path = ckpt.save(str(tmp_path / "a"), s_mesh)

    s_plain = _session(12, mesh=None, seed=99)
    ckpt.restore(path, s_plain)
    assert s_plain.round == 2
    assert s_plain.client_state["error"].shape[0] == 12  # no padding rows
    np.testing.assert_allclose(
        np.asarray(s_plain.client_state["error"]),
        np.asarray(s_mesh.client_state["error"])[:12], rtol=1e-6,
    )
    # and back into a fresh mesh session: re-padded, re-sharded
    s_mesh2 = _session(12, mesh=mesh, seed=100)
    ckpt.restore(path, s_mesh2)
    assert s_mesh2.client_state["error"].shape[0] == 16
    assert s_mesh2.client_state["error"].sharding.spec == P(meshlib.CLIENT_AXIS)
    np.testing.assert_allclose(
        np.asarray(s_mesh2.client_state["error"])[:12],
        np.asarray(s_mesh.client_state["error"])[:12], rtol=1e-6,
    )
    # both resumed sessions continue identically (same restored host rng)
    m1 = s_plain.run_round(0.05)
    m2 = s_mesh2.run_round(0.05)
    np.testing.assert_allclose(m1["loss_sum"], m2["loss_sum"], rtol=1e-5)


def test_local_topk_down_bytes_capped_at_dense():
    """Virtual server momentum carries past rounds' coordinates, so the
    broadcast support grows; accounting must cap at the dense-float cost."""
    from commefficient_tpu.utils.comm import BYTES_F32

    params = _init_mlp(jax.random.PRNGKey(0), 10, 16, 4)
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(mode="local_topk", d=d, k=d // 2, momentum_type="virtual",
                      error_type="none", num_clients=16)
    s = FederatedSession(
        train_loss_fn=_mlp_loss(10, 16, 4), eval_loss_fn=_mlp_loss(10, 16, 4),
        params=params, net_state={}, mode_cfg=mcfg,
        train_set=_dataset(16, 4, 10, 4), num_workers=8, local_batch_size=4,
    )
    dense_mb = d * BYTES_F32 * 8 / 1e6
    for _ in range(6):  # momentum accumulates support over rounds
        m = s.run_round(0.1)
        assert m["comm_down_mb"] <= dense_mb * 1.000001


def test_local_topk_down_bytes_measured_not_worst_case():
    """comm_down_mb reflects the actual transmitted support, bounded by the
    static worst case min(W*k, d)."""
    s = _session(16, k=8)
    m = s.run_round(0.1)
    worst = min(8 * 8, s.cfg.mode.d) * BYTES_PAIR * 8 / 1e6
    assert 0 < m["comm_down_mb"] <= worst * 1.000001
    support = m["comm_down_mb"] * 1e6 / (BYTES_PAIR * 8)
    assert support == int(support)  # integral pair count
    assert "down_support" not in m  # folded into the comm figures


def test_sharded_client_state_hybrid_mesh_matches_unsharded():
    """Same parity on a 2-slice x 4-device hybrid (DCN x ICI) mesh: the
    [num_clients, d] state shards over (slices, clients) and the round still
    matches the single-device session."""
    hmesh = meshlib.make_mesh(8, num_slices=2)
    s_ref = _session(16, mesh=None, seed=5)
    s_mesh = _session(16, mesh=hmesh, seed=5)
    for _ in range(3):
        s_ref.run_round(0.1)
        s_mesh.run_round(0.1)
    np.testing.assert_allclose(
        np.asarray(ravel_pytree(s_ref.state["params"])[0]),
        np.asarray(ravel_pytree(s_mesh.state["params"])[0]),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(s_ref.client_state["error"]),
        np.asarray(s_mesh.client_state["error"]),
        rtol=1e-5, atol=1e-6,
    )
