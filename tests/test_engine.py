"""Round-engine tests (SURVEY.md §4 integration list): `uncompressed` matches
plain SGD bit-for-bit (the reference's control mode); fedavg with 1 local iter
matches SGD; sharded-over-8-CPU-devices result matches unsharded; loss falls
under every mode on a tiny synthetic problem."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.flatten_util import ravel_pytree
from jax.sharding import NamedSharding, PartitionSpec as P

from commefficient_tpu.federated import engine
from commefficient_tpu.modes import modes
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.parallel import mesh as meshlib


def init_mlp(key, din=10, dh=16, dout=4):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (din, dh)) * 0.1,
        "b1": jnp.zeros(dh),
        "w2": jax.random.normal(k2, (dh, dout)) * 0.1,
        "b2": jnp.zeros(dout),
    }


def mlp_loss(params, net_state, batch, rng):
    h = jnp.tanh(batch["x"] @ params["w1"] + params["b1"])
    logits = h @ params["w2"] + params["b2"]
    logp = jax.nn.log_softmax(logits)
    per_ex = -jnp.take_along_axis(logp, batch["y"][:, None], axis=1)[:, 0]
    mask = batch["mask"]
    count = jnp.maximum(mask.sum(), 1.0)
    loss = (per_ex * mask).sum() / count
    correct = ((logits.argmax(-1) == batch["y"]) * mask).sum()
    return loss, {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum(), "correct": correct},
    }


def _data(key, n, din=10, dout=4):
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (n, din))
    w_true = jax.random.normal(kw, (din, dout))
    y = (x @ w_true).argmax(-1)
    return {"x": x, "y": y, "mask": jnp.ones(n)}


def _ucfg(**kw):
    base = dict(mode="uncompressed", d=0, momentum_type="none", error_type="none")
    base.update(kw)
    return base


def _make(cfg_kw, wd=0.0, **eng_kw):
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(**{**cfg_kw, "d": d})
    cfg = engine.EngineConfig(mode=mcfg, weight_decay=wd, **eng_kw)
    state = engine.init_server_state(cfg, params, {})
    step = jax.jit(engine.make_round_step(mlp_loss, cfg))
    return cfg, state, step


def test_uncompressed_matches_plain_sgd():
    data = _data(jax.random.PRNGKey(1), 16)
    batch = jax.tree.map(lambda a: a[None], data)  # W=1
    cfg, state, step = _make(_ucfg())
    lr = jnp.float32(0.2)

    # manual SGD on the same loss
    params = init_mlp(jax.random.PRNGKey(0))
    for i in range(5):
        state, _, metrics = step(state, batch, {}, lr, jax.random.PRNGKey(i))
        g = jax.grad(lambda p: mlp_loss(p, {}, data, None)[0])(params)
        params = jax.tree.map(lambda p, gg: p - lr * gg, params, g)
    for a, b in zip(jax.tree.leaves(state["params"]), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_uncompressed_momentum_weight_decay_matches_manual():
    data = _data(jax.random.PRNGKey(2), 16)
    batch = jax.tree.map(lambda a: a[None], data)
    cfg, state, step = _make(_ucfg(momentum_type="virtual", momentum=0.9), wd=0.01)
    lr = jnp.float32(0.1)

    params = init_mlp(jax.random.PRNGKey(0))
    vel = jax.tree.map(jnp.zeros_like, params)
    for i in range(4):
        state, _, _ = step(state, batch, {}, lr, jax.random.PRNGKey(i))
        g = jax.grad(lambda p: mlp_loss(p, {}, data, None)[0])(params)
        g = jax.tree.map(lambda gg, p: gg + 0.01 * p, g, params)
        vel = jax.tree.map(lambda v, gg: 0.9 * v + gg, vel, g)
        params = jax.tree.map(lambda p, v: p - lr * v, params, vel)
    for a, b in zip(jax.tree.leaves(state["params"]), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_fedavg_single_local_iter_matches_sgd():
    data = _data(jax.random.PRNGKey(3), 8)
    batch = jax.tree.map(lambda a: a[None, None], data)  # W=1, L=1
    cfg, state, step = _make(
        dict(mode="fedavg", momentum_type="none", error_type="none", num_local_iters=1)
    )
    lr = jnp.float32(0.2)
    state, _, _ = step(state, batch, {}, lr, jax.random.PRNGKey(0))

    params = init_mlp(jax.random.PRNGKey(0))
    g = jax.grad(lambda p: mlp_loss(p, {}, data, None)[0])(params)
    params = jax.tree.map(lambda p, gg: p - lr * gg, params, g)
    for a, b in zip(jax.tree.leaves(state["params"]), jax.tree.leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_multi_client_mean_equals_big_batch():
    """W clients with equal shards == one client with the union (uniform
    client weighting; shards equal-sized so the means coincide)."""
    data = _data(jax.random.PRNGKey(4), 32)
    w4 = jax.tree.map(lambda a: a.reshape((4,) + (8,) + a.shape[1:]), data)
    one = jax.tree.map(lambda a: a[None], data)
    lr = jnp.float32(0.1)
    cfg, state4, step = _make(_ucfg())
    _, state1, _ = _make(_ucfg())
    s4, _, m4 = step(state4, w4, {}, lr, jax.random.PRNGKey(0))
    s1, _, m1 = step(state1, one, {}, lr, jax.random.PRNGKey(0))
    for a, b in zip(jax.tree.leaves(s4["params"]), jax.tree.leaves(s1["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
    assert float(m4["count"]) == float(m1["count"]) == 32.0


def test_sharded_equals_unsharded():
    """The same step over an 8-device CPU mesh (client axis sharded) produces
    the same new params — 'distributed without a cluster' (SURVEY.md §4)."""
    mesh = meshlib.make_mesh(8)
    data = _data(jax.random.PRNGKey(5), 64)
    w8 = jax.tree.map(lambda a: a.reshape((8,) + (8,) + a.shape[1:]), data)
    lr = jnp.float32(0.1)
    cfg, state, step = _make(_ucfg())
    ref, _, _ = step(state, w8, {}, lr, jax.random.PRNGKey(0))

    _, state2, _ = _make(_ucfg())
    sharded_batch = meshlib.shard_client_batch(mesh, w8)
    got, _, _ = step(state2, sharded_batch, {}, lr, jax.random.PRNGKey(0))
    for a, b in zip(jax.tree.leaves(got["params"]), jax.tree.leaves(ref["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_fedavg_local_momentum_matches_manual():
    """momentum_type='local': heavy-ball momentum inside the local-SGD loop.
    One client, 3 local iters — compare against a hand-rolled momentum SGD."""
    data = _data(jax.random.PRNGKey(9), 12)
    micro = jax.tree.map(lambda a: a.reshape((1, 3, 4) + a.shape[1:]), data)
    lr, mu = 0.1, 0.5
    cfg, state, step = _make(
        dict(mode="fedavg", d=0, momentum_type="local", momentum=mu,
             error_type="none", num_local_iters=3)
    )
    new_state, _, _ = step(state, micro, {}, jnp.float32(lr), jax.random.PRNGKey(0))

    # manual: p_{t+1} = p_t - lr * m_t,  m_t = mu m_{t-1} + g_t
    params = init_mlp(jax.random.PRNGKey(0))
    pflat, unravel = ravel_pytree(params)
    m = np.zeros_like(pflat)
    p = np.asarray(pflat)
    for i in range(3):
        mb = jax.tree.map(lambda a: a[0, i], micro)
        g = ravel_pytree(jax.grad(lambda pp: mlp_loss(pp, {}, mb, None)[0])(unravel(jnp.asarray(p))))[0]
        m = mu * m + np.asarray(g)
        p = p - lr * m
    # server applies the averaged delta at server_lr = 1
    np.testing.assert_allclose(
        np.asarray(ravel_pytree(new_state["params"])[0]), p, rtol=1e-5, atol=1e-6
    )


def test_fedavg_server_lr_scales_delta():
    data = _data(jax.random.PRNGKey(10), 16)
    batch = jax.tree.map(lambda a: a.reshape((2, 2, 4) + a.shape[1:]), data)
    base = dict(mode="fedavg", d=0, momentum_type="none", error_type="none",
                num_local_iters=2)
    _, s1, step1 = _make(base)
    _, s2, step2 = _make({**base, "server_lr": 0.5})
    n1, _, _ = step1(s1, batch, {}, jnp.float32(0.1), jax.random.PRNGKey(0))
    n2, _, _ = step2(s2, batch, {}, jnp.float32(0.1), jax.random.PRNGKey(0))
    d1 = _flat_delta(s1, n1)
    d2 = _flat_delta(s2, n2)
    np.testing.assert_allclose(d2, 0.5 * d1, rtol=1e-5, atol=1e-7)


# ------------------------------------------------- differential privacy

def _flat_delta(state_before, state_after):
    a = ravel_pytree(state_before["params"])[0]
    b = ravel_pytree(state_after["params"])[0]
    return np.asarray(a - b)


def test_dp_clip_bounds_update_norm():
    """With a tiny clip, the server delta norm is ≤ lr·clip (uncompressed mode,
    W clipped client updates averaged then scaled by lr)."""
    data = _data(jax.random.PRNGKey(7), 32)
    batch = jax.tree.map(lambda a: a.reshape((4, 8) + a.shape[1:]), data)
    lr = 0.5
    clip = 1e-3
    cfg, state, step = _make(_ucfg(), dp_clip=clip)
    new_state, _, _ = step(state, batch, {}, jnp.float32(lr), jax.random.PRNGKey(0))
    delta = _flat_delta(state, new_state)
    assert np.linalg.norm(delta) <= lr * clip * 1.001
    # and with a huge clip the step matches the unclipped engine exactly
    cfg2, state2, step2 = _make(_ucfg(), dp_clip=1e9)
    cfg3, state3, step3 = _make(_ucfg())
    s2, _, _ = step2(state2, batch, {}, jnp.float32(lr), jax.random.PRNGKey(0))
    s3, _, _ = step3(state3, batch, {}, jnp.float32(lr), jax.random.PRNGKey(0))
    np.testing.assert_allclose(
        ravel_pytree(s2["params"])[0], ravel_pytree(s3["params"])[0], rtol=1e-6
    )


def test_dp_noise_perturbs_deterministically():
    """Same rng ⇒ identical noised step; different rng ⇒ different params;
    noise magnitude scales with the multiplier."""
    data = _data(jax.random.PRNGKey(8), 16)
    batch = jax.tree.map(lambda a: a.reshape((2, 8) + a.shape[1:]), data)
    lr = jnp.float32(0.1)

    def run(noise, key):
        cfg, state, step = _make(_ucfg(), dp_clip=1.0, dp_noise=noise)
        new_state, _, _ = step(state, batch, {}, lr, key)
        return ravel_pytree(new_state["params"])[0]

    p_a = run(0.5, jax.random.PRNGKey(0))
    p_b = run(0.5, jax.random.PRNGKey(0))
    p_c = run(0.5, jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(p_a), np.asarray(p_b))
    assert not np.allclose(np.asarray(p_a), np.asarray(p_c))
    # true_topk's dense wire is also a sound noise surface
    tcfg = dict(mode="true_topk", k=20, momentum_type="virtual", error_type="virtual")
    cfg, state, step = _make(tcfg, dp_clip=1.0, dp_noise=0.1)
    new_state, _, m = step(state, batch, {}, lr, jax.random.PRNGKey(0))
    assert np.isfinite(_flat_delta(state, new_state)).all()


def test_dp_noise_key_independent_of_client_keys():
    """The DP noise stream must never coincide with any client's rng: in
    threefry, fold_in(key, i) == split(key, n)[i], so deriving noise via
    fold_in from the same rng the client keys are split from collides at
    cohort sizes >= the folded constant (advisor finding, round 1). The
    engine splits a dedicated stream first; mirror that derivation here and
    assert no collision at a large cohort."""
    rng = jax.random.PRNGKey(123)
    num_sampled = 2048
    crng, noise_rng = jax.random.split(rng)
    client_keys = np.asarray(jax.random.split(crng, num_sampled))
    noise_keys = np.asarray(
        [jax.random.fold_in(noise_rng, i) for i in range(4)] + [noise_rng]
    )
    for nk in noise_keys:
        assert not (client_keys == nk[None, :]).all(axis=1).any()
    # and the old, broken derivation really does collide — the test's reason
    old_nkey = np.asarray(jax.random.fold_in(rng, 0x0D9))
    old_clients = np.asarray(jax.random.split(rng, num_sampled))
    assert (old_clients == old_nkey[None, :]).all(axis=1).any()


def test_dp_noise_rejects_unsound_surfaces():
    """Sketch tables (l1-scale worst-case sensitivity) and mutable model
    collections (BN stats bypass the mechanism) must be rejected."""
    with pytest.raises(ValueError):
        _make(
            dict(mode="sketch", k=20, num_rows=3, num_cols=100,
                 momentum_type="virtual", error_type="virtual"),
            dp_clip=1.0,
            dp_noise=0.1,
        )
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    cfg = engine.EngineConfig(
        mode=ModeConfig(**_ucfg(d=d)), dp_clip=1.0, dp_noise=0.1
    )
    with pytest.raises(ValueError):
        engine.init_server_state(cfg, params, {"batch_stats": {"m": jnp.zeros(3)}})


def test_dp_noise_requires_clip():
    with pytest.raises(ValueError):
        _make(_ucfg(), dp_noise=1.0)


def test_dp_noise_rejects_client_local_state():
    """topk(error_accumulator + update) has unbounded norm across rounds, so
    dp_clip cannot bound sensitivity — must be rejected, not silently unsound."""
    with pytest.raises(ValueError):
        _make(
            dict(mode="local_topk", k=50, momentum_type="none", error_type="local",
                 num_clients=4),
            dp_clip=1.0,
            dp_noise=0.5,
        )


@pytest.mark.parametrize(
    "cfg_kw",
    [
        _ucfg(),
        _ucfg(momentum_type="virtual"),
        dict(mode="sketch", k=50, num_rows=3, num_cols=200, momentum_type="virtual",
             error_type="virtual"),
        dict(mode="true_topk", k=50, momentum_type="virtual", error_type="virtual"),
        dict(mode="local_topk", k=50, momentum_type="none", error_type="local",
             num_clients=4),
        dict(mode="fedavg", momentum_type="none", error_type="none", num_local_iters=3),
    ],
    ids=["uncompressed", "uncompressed+mom", "sketch", "true_topk", "local_topk", "fedavg"],
)
def test_loss_decreases_every_mode(cfg_kw):
    W, B = 4, 16
    data = _data(jax.random.PRNGKey(6), W * B)
    if cfg_kw.get("mode") == "fedavg":
        L = cfg_kw["num_local_iters"]
        data = _data(jax.random.PRNGKey(6), W * L * B)
        batch = jax.tree.map(lambda a: a.reshape((W, L, B) + a.shape[1:]), data)
    else:
        batch = jax.tree.map(lambda a: a.reshape((W, B) + a.shape[1:]), data)
    cfg, state, step = _make(cfg_kw)
    rows = (
        jax.tree.map(lambda a: a[:W], modes.init_client_state(cfg.mode, 4))
        if cfg.mode.needs_local_state
        else {}
    )
    lr = jnp.float32(0.3)
    losses = []
    for i in range(12):
        state, rows, metrics = step(state, batch, rows, lr, jax.random.PRNGKey(i))
        losses.append(float(metrics["loss_sum"]) / float(metrics["count"]))
    assert losses[-1] < losses[0] * 0.7, losses


def test_hybrid_multislice_mesh_equals_unsharded():
    """A 2-slice x 4-device hybrid (DCN x ICI) mesh — BASELINE config #5 /
    SURVEY.md §7.7 — runs the same round step unchanged and matches the
    unsharded result: clients shard over (slices, clients), so the client
    mean lowers to an in-slice reduce plus one cross-slice all-reduce."""
    hmesh = meshlib.make_mesh(8, num_slices=2)
    assert dict(hmesh.shape) == {meshlib.DCN_AXIS: 2, meshlib.CLIENT_AXIS: 4}
    assert meshlib.client_shards(hmesh) == 8
    data = _data(jax.random.PRNGKey(5), 64)
    w8 = jax.tree.map(lambda a: a.reshape((8,) + (8,) + a.shape[1:]), data)
    lr = jnp.float32(0.1)
    cfg, state, step = _make(_ucfg())
    ref, _, _ = step(state, w8, {}, lr, jax.random.PRNGKey(0))

    _, state2, _ = _make(_ucfg())
    sharded = meshlib.shard_client_batch(hmesh, w8)
    got, _, _ = step(state2, sharded, {}, lr, jax.random.PRNGKey(0))
    for a, b in zip(jax.tree.leaves(got["params"]), jax.tree.leaves(ref["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_hybrid_mesh_with_model_axis():
    """3-axis hybrid mesh (slices, clients, model): the TP axis stays
    innermost (never crosses DCN) and client_shards counts slices x clients."""
    m = meshlib.make_mesh(8, model_parallel=2, num_slices=2)
    assert dict(m.shape) == {
        meshlib.DCN_AXIS: 2, meshlib.CLIENT_AXIS: 2, meshlib.MODEL_AXIS: 2
    }
    assert meshlib.client_shards(m) == 4
    assert meshlib.client_axes(m) == (meshlib.DCN_AXIS, meshlib.CLIENT_AXIS)


def test_sharded_eval_matches_unsharded():
    """evaluate() shards eval batches over the client axes (VERDICT r2 weak
    #4: eval must not run 1-device while training runs 8-way); metric totals
    must be identical because padded rows carry mask 0."""
    from commefficient_tpu.data.fed_dataset import FedDataset
    from commefficient_tpu.federated.api import FederatedSession

    rng = np.random.RandomState(0)
    n = 100  # deliberately not divisible by 8: exercises pad + round-up
    x = rng.randn(n, 10).astype(np.float32)
    w_true = rng.randn(10, 4).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int64)
    ds = FedDataset(x, y, [np.arange(i, n, 16) for i in range(16)])

    def build(mesh):
        return FederatedSession(
            train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss,
            params=init_mlp(jax.random.PRNGKey(0)), net_state={},
            mode_cfg=ModeConfig(**_ucfg(d=ravel_pytree(init_mlp(jax.random.PRNGKey(0)))[0].size)),
            train_set=ds, num_workers=8, local_batch_size=4, seed=1, mesh=mesh,
        )

    ref = build(None).evaluate(ds, batch_size=32)
    got = build(meshlib.make_mesh(8)).evaluate(ds, batch_size=32)
    got_hybrid = build(meshlib.make_mesh(8, num_slices=2)).evaluate(ds, batch_size=24)
    assert ref["count"] == got["count"] == got_hybrid["count"] == float(n)
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=1e-5)
        np.testing.assert_allclose(got_hybrid[k], ref[k], rtol=1e-5)


@pytest.mark.parametrize("mode_kw, eng_kw", [
    (dict(mode="sketch", k=16, num_rows=3, num_cols=1024,
          hash_family="rotation", momentum_type="virtual", error_type="virtual"),
     {}),
    (dict(mode="uncompressed", d=0, momentum_type="virtual", error_type="none"),
     dict(dp_clip=1.0, dp_noise=0.5, client_dropout=0.3)),
    # chunked client phase under the split engine: the composition the
    # GPT-2-scale bench relies on (BENCH_CLIENT_CHUNK + split compile)
    (dict(mode="sketch", k=16, num_rows=3, num_cols=1024,
          hash_family="rotation", momentum_type="virtual", error_type="virtual"),
     dict(client_chunk=4)),
])
def test_split_round_step_matches_fused(mode_kw, eng_kw):
    """The two-program split (Mosaic-isolating) round must equal the fused
    step bit-for-bit: same rng streams, same linear-mode shortcut — including
    under DP noise + dropout, whose sensitivity scaling crosses the program
    boundary as the participants scalar."""
    W = 8
    data = _data(jax.random.PRNGKey(1), W * 4)
    batch = jax.tree.map(lambda a: a.reshape((W, 4) + a.shape[1:]), data)
    lr = jnp.float32(0.1)

    cfg, state_f, fused = _make(dict(mode_kw), wd=5e-4, **eng_kw)
    _, state_s, _ = _make(dict(mode_kw), wd=5e-4, **eng_kw)
    client_p, server_p = engine.make_split_round_step(mlp_loss, cfg)
    cstep = jax.jit(client_p)
    sstep = jax.jit(server_p, donate_argnums=(0,))

    for i in range(3):
        rng = jax.random.PRNGKey(10 + i)
        state_f, _, m_f = fused(state_f, batch, {}, lr, rng)
        weighted, nns, m_s, nrng = cstep(state_s, batch, lr, rng)
        state_s = sstep(state_s, weighted, nns, m_s["participants"], lr, nrng)
        assert float(m_f["loss_sum"]) == float(m_s["loss_sum"])
        assert float(m_f["participants"]) == float(m_s["participants"])
    for a, b in zip(jax.tree.leaves(state_f["params"]), jax.tree.leaves(state_s["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(state_f["mode_state"]), jax.tree.leaves(state_s["mode_state"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_split_round_step_rejects_nonlinear_scope():
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    for kw in (
        dict(mode="local_topk", d=d, k=8, momentum_type="none", error_type="local",
             num_clients=4),
        dict(mode="fedavg", d=d, num_local_iters=2, error_type="none",
             momentum_type="none"),
    ):
        cfg = engine.EngineConfig(mode=ModeConfig(**kw))
        with pytest.raises(ValueError, match="fused"):
            engine.make_split_round_step(mlp_loss, cfg)


def test_split_session_matches_fused_session():
    """FederatedSession(split_compile=True) runs the same rounds as the fused
    session — sampling, metrics, comm accounting, and params all equal."""
    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated.api import FederatedSession

    rngd = np.random.RandomState(0)
    n = 64
    x = rngd.normal(size=(n, 10)).astype(np.float32)
    y = rngd.randint(0, 4, size=n).astype(np.int32)

    def make(split):
        params = init_mlp(jax.random.PRNGKey(0))
        d = ravel_pytree(params)[0].size
        return FederatedSession(
            train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss,
            params=jax.tree.map(jnp.copy, params), net_state={},
            mode_cfg=ModeConfig(mode="sketch", d=d, k=16, num_rows=3,
                                num_cols=1024, hash_family="rotation",
                                momentum_type="virtual", error_type="virtual"),
            train_set=FedDataset(x, y, shard_iid(n, 16, np.random.RandomState(1))),
            num_workers=8, local_batch_size=2, seed=7, split_compile=split,
        )

    a, b = make(False), make(True)
    for _ in range(3):
        ma = a.run_round(0.1)
        mb = b.run_round(0.1)
        assert ma == mb
    np.testing.assert_array_equal(
        np.asarray(ravel_pytree(a.state["params"])[0]),
        np.asarray(ravel_pytree(b.state["params"])[0]),
    )


@pytest.mark.parametrize("chunk", [2, 4, 8])
def test_client_chunked_reduce_matches_unchunked(chunk):
    """cfg.client_chunk scans the grads in chunks accumulating additively —
    equal to the one-shot vmap up to fp summation order, for both the fused
    and the split step, with dropout active."""
    W = 8
    data = _data(jax.random.PRNGKey(1), W * 4)
    batch = jax.tree.map(lambda a: a.reshape((W, 4) + a.shape[1:]), data)
    lr, rng = jnp.float32(0.1), jax.random.PRNGKey(9)
    kw = dict(mode="sketch", k=16, num_rows=3, num_cols=1024,
              hash_family="rotation", momentum_type="virtual", error_type="virtual")

    cfg0, s0, step0 = _make(dict(kw), wd=5e-4, client_dropout=0.3)
    cfgC, sC, stepC = _make(dict(kw), wd=5e-4, client_dropout=0.3,
                            client_chunk=chunk)
    a, _, ma = step0(s0, batch, {}, lr, rng)
    b, _, mb = stepC(sC, batch, {}, lr, rng)
    assert float(ma["participants"]) == float(mb["participants"])
    np.testing.assert_allclose(float(ma["loss_sum"]), float(mb["loss_sum"]), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(ravel_pytree(a["params"])[0]),
        np.asarray(ravel_pytree(b["params"])[0]), rtol=1e-5, atol=1e-7,
    )

    # split step honors the same knob
    client_p, server_p = engine.make_split_round_step(
        mlp_loss, engine.EngineConfig(mode=ModeConfig(**{**kw, "d": cfg0.mode.d}),
                                      weight_decay=5e-4, client_dropout=0.3,
                                      client_chunk=chunk))
    _, sS, _ = _make(dict(kw), wd=5e-4, client_dropout=0.3)
    w, nns, ms, nrng = jax.jit(client_p)(sS, batch, lr, rng)
    sS = jax.jit(server_p)(sS, w, nns, ms["participants"], lr, nrng)
    np.testing.assert_allclose(
        np.asarray(ravel_pytree(a["params"])[0]),
        np.asarray(ravel_pytree(sS["params"])[0]), rtol=1e-5, atol=1e-7,
    )


def test_client_chunk_must_divide_cohort():
    W = 8
    data = _data(jax.random.PRNGKey(1), W * 4)
    batch = jax.tree.map(lambda a: a.reshape((W, 4) + a.shape[1:]), data)
    _, state, step = _make(_ucfg(), client_chunk=3)
    with pytest.raises(ValueError, match="divide"):
        step(state, batch, {}, jnp.float32(0.1), jax.random.PRNGKey(0))


def test_client_chunked_sharded_matches_unsharded():
    """Chunking composes with the client mesh: each chunk's vmap stays
    sharded over the client axis."""
    from commefficient_tpu.parallel import mesh as meshlib

    mesh = meshlib.make_mesh(8)
    data = _data(jax.random.PRNGKey(5), 64)
    w16 = jax.tree.map(lambda a: a.reshape((16, 4) + a.shape[1:]), data)
    lr, rng = jnp.float32(0.1), jax.random.PRNGKey(4)
    _, s_ref, step_ref = _make(_ucfg(), client_chunk=4)
    ref, _, mref = step_ref(s_ref, w16, {}, lr, rng)
    _, s_m, step_m = _make(_ucfg(), client_chunk=4)
    got, _, mgot = step_m(s_m, meshlib.shard_client_batch(mesh, w16), {}, lr, rng)
    np.testing.assert_allclose(
        np.asarray(ravel_pytree(got["params"])[0]),
        np.asarray(ravel_pytree(ref["params"])[0]), rtol=1e-5, atol=1e-6,
    )
    assert float(mgot["count"]) == float(mref["count"])


def test_session_adjusts_client_chunk_to_cohort():
    """Constructor-time safety: cohort clamping/rounding can invalidate the
    requested chunk; the session must adjust it (largest viable divisor)
    rather than crash at the first jit trace."""
    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated.api import FederatedSession

    rngd = np.random.RandomState(0)
    n = 64
    x = rngd.normal(size=(n, 10)).astype(np.float32)
    y = rngd.randint(0, 4, size=n).astype(np.int32)
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    s = FederatedSession(
        train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss, params=params,
        net_state={}, mode_cfg=ModeConfig(**_ucfg(d=d)),
        train_set=FedDataset(x, y, shard_iid(n, 16, rngd)),
        num_workers=12, local_batch_size=2,
        mesh=meshlib.make_mesh(8),  # rounds cohort 12 -> 16
        client_chunk=6,             # divided 12; no longer divides 16
    )
    # on the 8-way mesh the SPMD round scans chunks WITHIN each shard, so
    # the chunk adjusts to the per-shard cohort (16/8 = 2), not the global 16
    assert s.num_workers == 16 and s.cfg.client_shards == 8
    assert s.cfg.client_chunk == 2
    m = s.run_round(0.1)  # and the round actually runs chunked
    assert np.isfinite(m["loss_sum"])


def test_negative_client_chunk_rejected():
    with pytest.raises(ValueError, match="client_chunk"):
        _make(_ucfg(), client_chunk=-2)


def test_multi_round_dispatch_matches_sequential():
    """engine.make_multi_round_step: K rounds in one lax.scan == K sequential
    step calls, bit-for-bit (same rng streams via the caller)."""
    kw = dict(mode="sketch", k=16, num_rows=3, num_cols=1024,
              hash_family="rotation", momentum_type="virtual", error_type="virtual")
    W, K = 4, 3
    data = _data(jax.random.PRNGKey(1), W * 4 * K)
    all_b = jax.tree.map(lambda a: a.reshape((K, W, 4) + a.shape[1:]), data)
    lrs = jnp.asarray([0.1, 0.2, 0.05], jnp.float32)
    rngs = jax.random.split(jax.random.PRNGKey(7), K)

    cfg, state_s, step = _make(dict(kw), wd=5e-4)
    _, state_m, _ = _make(dict(kw), wd=5e-4)
    seq_metrics = []
    for i in range(K):
        b = jax.tree.map(lambda a: a[i], all_b)
        state_s, _, m = step(state_s, b, {}, lrs[i], rngs[i])
        seq_metrics.append(m)
    multi = jax.jit(engine.make_multi_round_step(mlp_loss, cfg))
    state_m, ms = multi(state_m, all_b, lrs, rngs)
    for a, b in zip(jax.tree.leaves(state_s["params"]), jax.tree.leaves(state_m["params"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for i, m in enumerate(seq_metrics):
        for k2, v in m.items():
            np.testing.assert_allclose(float(v), float(ms[k2][i]), rtol=1e-6)


def test_multi_round_rejects_local_state_modes():
    params = init_mlp(jax.random.PRNGKey(0))
    d = ravel_pytree(params)[0].size
    cfg = engine.EngineConfig(mode=ModeConfig(
        mode="local_topk", d=d, k=8, momentum_type="none", error_type="local",
        num_clients=4))
    with pytest.raises(ValueError, match="run_round"):
        engine.make_multi_round_step(mlp_loss, cfg)


def test_session_run_rounds_matches_run_round():
    """FederatedSession.run_rounds: identical sampling/rng/metrics/comm to
    sequential run_round calls, on the sharded mesh, one dispatch."""
    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated.api import FederatedSession

    rngd = np.random.RandomState(0)
    n = 64
    x = rngd.normal(size=(n, 10)).astype(np.float32)
    y = rngd.randint(0, 4, size=n).astype(np.int32)

    def make():
        params = init_mlp(jax.random.PRNGKey(0))
        d = ravel_pytree(params)[0].size
        return FederatedSession(
            train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss,
            params=jax.tree.map(jnp.copy, params), net_state={},
            mode_cfg=ModeConfig(mode="sketch", d=d, k=16, num_rows=3,
                                num_cols=1024, hash_family="rotation",
                                momentum_type="virtual", error_type="virtual"),
            train_set=FedDataset(x, y, shard_iid(n, 16, np.random.RandomState(1))),
            num_workers=8, local_batch_size=2, seed=7,
            mesh=meshlib.make_mesh(8), client_dropout=0.25,
        )

    a, b = make(), make()
    seq = [a.run_round(lr) for lr in (0.1, 0.2, 0.05, 0.1)]
    blk = b.run_rounds([0.1, 0.2, 0.05, 0.1])
    assert len(blk) == 4
    for ma, mb in zip(seq, blk):
        assert set(ma) == set(mb)
        for k2 in ma:
            np.testing.assert_allclose(ma[k2], mb[k2], rtol=1e-5)
    assert a.round == b.round == 4
    np.testing.assert_allclose(a.comm_mb_total, b.comm_mb_total, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(ravel_pytree(a.state["params"])[0]),
        np.asarray(ravel_pytree(b.state["params"])[0]), rtol=1e-5, atol=1e-7,
    )


def test_plan_block_boundaries():
    """plan_block truncates at run end and eval/checkpoint boundaries and
    advances the schedule exactly once per planned round."""
    from commefficient_tpu.federated.api import FedOptimizer, plan_block

    opt = FedOptimizer(lambda e: 0.1, rounds_per_epoch=4)
    # eval boundary at 8: from rnd=6 with k=8 the block is 2
    assert len(plan_block(opt, 6, 100, 8, 0, 8)) == 2
    assert opt.round == 2
    # checkpoint boundary at 3 binds tighter than eval at 8 from rnd=1
    assert len(plan_block(opt, 1, 100, 8, 3, 8)) == 2
    # run end binds from rnd=98
    assert len(plan_block(opt, 98, 100, 8, 0, 8)) == 2
    # k=1 is always a single round
    assert len(plan_block(opt, 0, 100, 8, 0, 1)) == 1


def test_session_run_rounds_hybrid_mesh():
    """Block dispatch on the (slices, clients) DCN x ICI mesh: the stacked
    [K, W, ...] batch shards its client axis over both axes and the rounds
    match the plain-mesh session."""
    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated.api import FederatedSession

    rngd = np.random.RandomState(0)
    n = 64
    x = rngd.normal(size=(n, 10)).astype(np.float32)
    y = rngd.randint(0, 4, size=n).astype(np.int32)

    def make(mesh):
        params = init_mlp(jax.random.PRNGKey(0))
        d = ravel_pytree(params)[0].size
        return FederatedSession(
            train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss,
            params=jax.tree.map(jnp.copy, params), net_state={},
            mode_cfg=ModeConfig(mode="sketch", d=d, k=16, num_rows=3,
                                num_cols=1024, hash_family="rotation",
                                momentum_type="virtual", error_type="virtual"),
            train_set=FedDataset(x, y, shard_iid(n, 16, np.random.RandomState(1))),
            num_workers=8, local_batch_size=2, seed=7, mesh=mesh,
        )

    a = make(meshlib.make_mesh(8))
    b = make(meshlib.make_mesh(8, num_slices=2))
    ma = a.run_rounds([0.1, 0.2])
    mb = b.run_rounds([0.1, 0.2])
    for ra, rb in zip(ma, mb):
        np.testing.assert_allclose(ra["loss_sum"], rb["loss_sum"], rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(ravel_pytree(a.state["params"])[0]),
        np.asarray(ravel_pytree(b.state["params"])[0]), rtol=1e-5, atol=1e-6,
    )


def test_run_rounds_local_topk_virtual_downlink_accounting():
    """Block dispatch with local_topk (error_type=virtual — stateless, so
    eligible): the per-round measured down_support must fold into comm
    accounting identically to sequential rounds."""
    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated.api import FederatedSession

    rngd = np.random.RandomState(0)
    n = 64
    x = rngd.normal(size=(n, 10)).astype(np.float32)
    y = rngd.randint(0, 4, size=n).astype(np.int32)

    def make():
        params = init_mlp(jax.random.PRNGKey(0))
        d = ravel_pytree(params)[0].size
        return FederatedSession(
            train_loss_fn=mlp_loss, eval_loss_fn=mlp_loss,
            params=jax.tree.map(jnp.copy, params), net_state={},
            mode_cfg=ModeConfig(mode="local_topk", d=d, k=16,
                                momentum_type="none", error_type="virtual"),
            train_set=FedDataset(x, y, shard_iid(n, 16, np.random.RandomState(1))),
            num_workers=8, local_batch_size=2, seed=7,
        )

    a, b = make(), make()
    seq = [a.run_round(0.1) for _ in range(3)]
    blk = b.run_rounds([0.1, 0.1, 0.1])
    for ma, mb in zip(seq, blk):
        assert "down_support" not in mb  # folded into the comm figures
        np.testing.assert_allclose(ma["comm_down_mb"], mb["comm_down_mb"], rtol=1e-6)
        np.testing.assert_allclose(ma["comm_total_mb"], mb["comm_total_mb"], rtol=1e-6)


def test_localsgd_single_iter_matches_uncompressed():
    """mode=localSGD (SURVEY.md §2 L2: the sixth mode — zero coverage until
    round 4): with 1 local iteration and no momentum anywhere, the client's
    weight delta is exactly lr*grad and the server applies the survivor mean
    at unit rate — bit-for-bit the uncompressed control on the same rounds."""
    data = _data(jax.random.PRNGKey(11), 24)
    batch = jax.tree.map(lambda a: a.reshape((3, 1, 8) + a.shape[1:]), data)
    lr = jnp.float32(0.15)
    cfg_l, state_l, step_l = _make(
        dict(mode="localSGD", momentum_type="none", error_type="none",
             num_local_iters=1))
    cfg_u, state_u, step_u = _make(_ucfg(momentum_type="none"))
    ubatch = jax.tree.map(lambda a: a.reshape((3, 8) + a.shape[1:]), data)
    for i in range(3):
        state_l, _, _ = step_l(state_l, batch, {}, lr, jax.random.PRNGKey(i))
        state_u, _, _ = step_u(state_u, ubatch, {}, lr, jax.random.PRNGKey(i))
    for a, b in zip(jax.tree.leaves(state_l["params"]),
                    jax.tree.leaves(state_u["params"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_localsgd_virtual_momentum_multi_iter():
    """localSGD's own niche vs fedavg: SERVER (virtual) momentum over
    multi-iter weight deltas — V = rho*V + mean(delta), applied at
    server_lr=1. Pinned against a manual replay of the algebra."""
    data = _data(jax.random.PRNGKey(12), 12)
    micro = jax.tree.map(lambda a: a.reshape((1, 3, 4) + a.shape[1:]), data)
    lr, rho = jnp.float32(0.1), 0.6
    cfg, state, step = _make(
        dict(mode="localSGD", momentum_type="virtual", momentum=rho,
             error_type="none", num_local_iters=3))
    p0 = jax.tree.map(jnp.copy, state["params"])
    s1, _, _ = step(state, micro, {}, lr, jax.random.PRNGKey(0))
    s2, _, _ = step(s1, micro, {}, lr, jax.random.PRNGKey(1))

    # manual: delta_t = 3-step local SGD from the server params; V accumulates
    from jax.flatten_util import ravel_pytree as rav

    def local_delta(params, rng):
        pflat, unravel = rav(params)
        p = pflat
        rngs = jax.random.split(rng, 3)
        for j in range(3):
            mb = jax.tree.map(lambda a: a[0, j], micro)
            g = jax.grad(lambda q: mlp_loss(unravel(q), {}, mb, rngs[j])[0])(p)
            p = p - lr * g
        return pflat - p

    pflat0, unravel = rav(p0)
    V = jnp.zeros_like(pflat0)
    p = pflat0
    for i in range(2):
        V = rho * V + local_delta(unravel(p), jax.random.split(
            jax.random.split(jax.random.PRNGKey(i), 3)[0], 1)[0])
        p = p - V
    for a, b in zip(jax.tree.leaves(s2["params"]), jax.tree.leaves(unravel(p))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
