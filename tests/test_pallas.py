"""Pallas kernel tests (interpreter mode on the CPU mesh): the rotation-family
accumulate/query kernels must match the pure-JAX oracle in csvec.py, which the
property tests in test_csvec.py already pin to the generic hash path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.sketch import CSVecSpec, csvec
from commefficient_tpu.sketch import pallas_kernels as pk

# small enough for the interpreter, c % 128 == 0, d not a multiple of c
SPEC = CSVecSpec(d=3000, c=1024, r=3, seed=13, family="rotation")


def _v(key, d):
    return jax.random.normal(jax.random.PRNGKey(key), (d,), jnp.float32)


def test_supported_layouts():
    assert pk.supported(SPEC)
    assert not pk.supported(CSVecSpec(d=3000, c=1000, r=3, family="rotation"))
    assert not pk.supported(CSVecSpec(d=3000, c=1024, r=3, family="random"))
    # bench dims are eligible; a table that can't stay VMEM-resident is not
    assert pk.supported(CSVecSpec(d=6_573_130, c=524_288, r=5, family="rotation"))
    assert pk.supported(CSVecSpec(d=124_000_000, c=1_048_576, r=5, family="rotation"))
    assert not pk.supported(CSVecSpec(d=124_000_000, c=8_388_608, r=5, family="rotation"))


def test_vmem_budget_selection():
    """Flagship dims keep the 48 MiB scoped limit (compile-cache stability);
    GPT-2 dims (c=2^20 r=5, whose accumulate kernel measures 48.21 MiB —
    the round-5 phase-E OOM) get the 96 MiB limit; the model stays an upper
    bound on Mosaic's measured footprint at the known calibration point."""
    small = pk._compiler_params(524_288, 5).vmem_limit_bytes
    large = pk._compiler_params(1_048_576, 5).vmem_limit_bytes
    assert small == pk._VMEM_SMALL_BYTES
    assert large == pk._VMEM_LARGE_BYTES
    # calibration: measured 48.21 MiB at c=2^20 r=5 must fit under the model
    assert pk._worst_case_vmem(1_048_576, 5) >= int(48.21 * 1024 * 1024)


def test_accumulate_matches_oracle():
    v = _v(0, SPEC.d)
    got = pk.sketch_vec(SPEC, v, interpret=True)
    want = csvec.sketch_vec(SPEC, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_query_matches_oracle():
    v = _v(1, SPEC.d)
    table = csvec.sketch_vec(SPEC, v)
    got = pk.query_all(SPEC, table, interpret=True)
    want = csvec.query_all(SPEC, table)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)


def test_single_slab_and_exact_multiple():
    """d < c (one slab) and d == S*c (no padding) both round-trip."""
    for d in (700, 2048):
        spec = CSVecSpec(d=d, c=1024, r=3, seed=5, family="rotation")
        v = _v(2, d)
        np.testing.assert_allclose(
            np.asarray(pk.sketch_vec(spec, v, interpret=True)),
            np.asarray(csvec.sketch_vec(spec, v)),
            rtol=1e-5,
            atol=1e-5,
        )
        t = csvec.sketch_vec(spec, v)
        np.testing.assert_allclose(
            np.asarray(pk.query_all(spec, t, interpret=True)),
            np.asarray(csvec.query_all(spec, t)),
            rtol=1e-6,
            atol=1e-6,
        )


def test_even_rows_lower_median():
    """r even exercises the lower-median convention in the kernel's sort."""
    spec = CSVecSpec(d=1500, c=256, r=4, seed=8, family="rotation")
    v = _v(3, spec.d)
    t = csvec.sketch_vec(spec, v)
    np.testing.assert_allclose(
        np.asarray(pk.query_all(spec, t, interpret=True)),
        np.asarray(csvec.query_all(spec, t)),
        rtol=1e-6,
        atol=1e-6,
    )


def test_probe_failure_falls_back_to_oracle(monkeypatch):
    """The library-level gate: when the per-layout probe reports a Mosaic
    failure on a TPU backend, sketch_vec/query_all silently use the pure-JAX
    oracle instead of crashing — and the status surfaces the traceback."""
    spec = CSVecSpec(d=3000, c=1024, r=3, seed=13, family="rotation")
    v = _v(7, spec.d)
    want = csvec._sketch_vec_rotation(spec, v)

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(
        pk, "probe", lambda c, r: (False, "MosaicError: simulated\n<traceback>")
    )
    assert not csvec._use_pallas(spec)
    got = csvec.sketch_vec(spec, v)  # must route to the oracle, not raise
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_probe_status_reports_errors():
    pk._PROBE.clear()
    assert pk.probe_status() == {"probed": False}
    pk._PROBE[(1024, 3)] = (True, None)
    pk._PROBE[(2048, 5)] = (False, "tb")
    st = pk.probe_status()
    assert st["probed"] and not st["ok"]
    assert st["errors"] == {"c=2048,r=5": "tb"}
    pk._PROBE.clear()

def test_engine_round_step_with_pallas_kernels(monkeypatch):
    """The EXACT composition that runs on hardware: the full federated round
    step (client grads -> aggregate -> sketch -> virtual momentum/error ->
    unsketch_topk) with the library routed to the Pallas kernels, pinned
    against the oracle-engine result. COMMEFFICIENT_PALLAS_INTERPRET=1 runs
    the kernels in the Pallas interpreter, so this passes on the CPU mesh —
    it proves the composition traces, jits, and is numerically equal; only
    the Mosaic/native compile of the same module remains hardware-only
    (scripts/tpu_round3.sh step 5)."""
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.federated import engine
    from commefficient_tpu.modes.config import ModeConfig

    from test_engine import _data, init_mlp, mlp_loss

    params = init_mlp(jax.random.PRNGKey(0), din=64, dh=128)
    d = ravel_pytree(params)[0].size
    assert d > 2 * 1024  # several slabs: the kernel grid loop is exercised
    data = _data(jax.random.PRNGKey(1), 24, din=64)
    batch = jax.tree.map(lambda a: a.reshape((4, 6) + a.shape[1:]), data)
    kw = dict(
        mode="sketch", d=d, k=32, num_rows=3, num_cols=1024,
        hash_family="rotation", momentum_type="virtual", error_type="virtual",
    )

    def run(pallas: bool):
        if pallas:
            monkeypatch.setenv("COMMEFFICIENT_PALLAS_INTERPRET", "1")
        else:
            monkeypatch.delenv("COMMEFFICIENT_PALLAS_INTERPRET", raising=False)
        cfg = engine.EngineConfig(mode=ModeConfig(**kw))
        assert csvec._use_pallas(cfg.mode.sketch_spec) == pallas
        state = engine.init_server_state(
            cfg, jax.tree.map(jnp.copy, params), {}
        )
        step = jax.jit(engine.make_round_step(mlp_loss, cfg))
        for i in range(3):
            state, _, _ = step(
                state, batch, {}, jnp.float32(0.1), jax.random.PRNGKey(i)
            )
        return ravel_pytree(state["params"])[0]

    got, want = run(pallas=True), run(pallas=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_split_engine_with_pallas_kernels(monkeypatch):
    """The wedge-avoidance composition for hardware: the SPLIT round (client
    grads | sketch server step) with the library routed to the Pallas kernels
    — only the small server program carries Mosaic custom-calls. Pinned
    against the fused oracle engine via the interpreter on the CPU mesh."""
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.federated import engine
    from commefficient_tpu.modes.config import ModeConfig

    from test_engine import _data, init_mlp, mlp_loss

    params = init_mlp(jax.random.PRNGKey(0), din=64, dh=128)
    d = ravel_pytree(params)[0].size
    data = _data(jax.random.PRNGKey(1), 24, din=64)
    batch = jax.tree.map(lambda a: a.reshape((4, 6) + a.shape[1:]), data)
    kw = dict(
        mode="sketch", d=d, k=32, num_rows=3, num_cols=1024,
        hash_family="rotation", momentum_type="virtual", error_type="virtual",
    )

    def run(split_pallas: bool):
        if split_pallas:
            monkeypatch.setenv("COMMEFFICIENT_PALLAS_INTERPRET", "1")
        else:
            monkeypatch.delenv("COMMEFFICIENT_PALLAS_INTERPRET", raising=False)
        cfg = engine.EngineConfig(mode=ModeConfig(**kw))
        state = engine.init_server_state(cfg, jax.tree.map(jnp.copy, params), {})
        lr = jnp.float32(0.1)
        if split_pallas:
            client_p, server_p = engine.make_split_round_step(mlp_loss, cfg)
            cstep, sstep = jax.jit(client_p), jax.jit(server_p)
            for i in range(3):
                w, nns, met, nrng = cstep(state, batch, lr, jax.random.PRNGKey(i))
                state = sstep(state, w, nns, met["participants"], lr, nrng)
        else:
            step = jax.jit(engine.make_round_step(mlp_loss, cfg))
            for i in range(3):
                state, _, _ = step(state, batch, {}, lr, jax.random.PRNGKey(i))
        return ravel_pytree(state["params"])[0]

    got, want = run(True), run(False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)
