"""Cross-process bit-determinism (SURVEY.md §5 "Race detection /
sanitizers: none" — the reference trusts its queue/shm protocol by
construction; here the one component with real concurrency is the
multithreaded C++ batch-assembly runtime, and this test is its race
detector: two fresh processes running the same seeded CLI config must
produce byte-identical JSONL metrics, which fails if native row assembly,
host RNG use, or any reduction is nondeterministic)."""

import json
import os
import subprocess
import sys


def _run(tmp_path, tag):
    from conftest import hermetic_subprocess_env, repo_root

    log = tmp_path / f"{tag}.jsonl"
    out = subprocess.run(
        [sys.executable, "cv_train.py", "--dataset", "cifar10",
         "--mode", "sketch", "--k", "256", "--num_cols", "4096",
         "--num_rows", "3", "--num_clients", "16", "--num_workers", "8",
         "--num_rounds", "4", "--eval_every", "2", "--seed", "7",
         "--local_batch_size", "4", "--log_jsonl", str(log)],
        capture_output=True, text=True, timeout=900,
        env=hermetic_subprocess_env(), cwd=repo_root(),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    # the point is race-detecting the MULTITHREADED native runtime: a silent
    # numpy fallback (no g++ / failed build) would make this pass vacuously
    assert "numpy fallback" not in out.stdout, out.stdout[-500:]
    return log.read_text()

def test_same_seed_two_processes_bit_identical(tmp_path):
    a, b = _run(tmp_path, "a"), _run(tmp_path, "b")
    rows_a = [json.loads(ln) for ln in a.splitlines()]
    assert rows_a and rows_a[-1]["round"] == 4
    # byte-identical logs EXCEPT the wall-clock column
    strip = lambda txt: [
        {k: v for k, v in json.loads(ln).items() if k != "time_s"}
        for ln in txt.splitlines()
    ]
    assert strip(a) == strip(b)
