"""Next-utterance classification (double-head) tests — VERDICT r2 #6: the
transfer-learning-conv-ai LM+MC objective the reference inherits (SURVEY.md
§3.2). Packing produces candidate sets with a shuffled gold position; the MC
head scores candidates; federated training drives MC accuracy above chance
on synthetic persona-vs-distractor data within a few rounds."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from commefficient_tpu.data.personachat import load_personachat_fed
from commefficient_tpu.federated import engine
from commefficient_tpu.models.gpt2 import TINY, GPT2LMHead
from commefficient_tpu.models.losses import make_lm_mc_loss
from commefficient_tpu.modes.config import ModeConfig

SEQ = 48
C = 2


def _dataset(num_clients=24, seed=3):
    return load_personachat_fed(
        "/nonexistent", num_clients, SEQ, seed, num_candidates=C
    )


def test_mc_packing_shapes_and_labels():
    train, valid, tok = _dataset()
    assert train.num_candidates == C and train.seq_len == SEQ
    rng = np.random.RandomState(0)
    ids = train.sample_clients(rng, 4)
    b = train.client_batch(rng, ids, 2)
    assert b["input_ids"].shape == (4, 2, C, SEQ)
    assert b["token_type_ids"].shape == (4, 2, C, SEQ)
    assert b["labels"].shape == (4, 2, C, SEQ)
    assert b["mc_label"].shape == (4, 2)
    filled = b["mc_label"] >= 0
    assert filled.any()
    # only the gold candidate carries LM labels; distractors are all -100
    for w, n in zip(*np.nonzero(filled)):
        gold = int(b["mc_label"][w, n])
        assert (b["labels"][w, n, gold] != -100).any()
        for c in range(C):
            if c != gold:
                assert (b["labels"][w, n, c] == -100).all()
    # padded rows are ignored by both losses
    for w, n in zip(*np.nonzero(~filled)):
        assert (b["labels"][w, n] == -100).all()


def test_mc_head_output_shapes():
    cfg = dataclasses.replace(TINY, n_positions=SEQ, with_mc_head=True)
    model = GPT2LMHead(cfg)
    ids = jnp.zeros((4, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    assert params["mc_head"].shape == (cfg.n_embd,)
    lm, mc = model.apply(
        {"params": params}, ids, train=False,
        mc_positions=jnp.array([5, 0, SEQ - 1, 7]),
    )
    assert lm.shape == (4, SEQ, cfg.vocab_size)
    assert mc.shape == (4,)
    # without positions, same params yield the plain LM path
    lm_only = model.apply({"params": params}, ids, train=False)
    np.testing.assert_allclose(np.asarray(lm_only), np.asarray(lm))


def test_mc_accuracy_rises_above_chance():
    """Joint LM+MC federated training separates gold replies from synthetic
    distractors (reserved-vocabulary marker — see _synthetic) well above the
    1/C chance rate within a few rounds."""
    train, _, tok = _dataset(num_clients=16, seed=5)
    cfg = dataclasses.replace(
        TINY, vocab_size=tok.vocab_size, n_positions=SEQ, with_mc_head=True
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32), train=False
    )["params"]
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(mode="uncompressed", d=d, momentum_type="virtual", error_type="none")
    ecfg = engine.EngineConfig(mode=mcfg)
    state = engine.init_server_state(ecfg, params, {})
    loss_fn = make_lm_mc_loss(model, train=True, mc_coef=16.0, pad_id=tok.pad_id)
    step = jax.jit(engine.make_round_step(loss_fn, ecfg))

    rng = np.random.RandomState(7)
    correct = count = 0.0
    rounds = 20
    for rnd in range(rounds):
        ids = train.sample_clients(rng, 8)
        batch = train.client_batch(rng, ids, 4)
        state, _, metrics = step(
            state, batch, {}, jnp.float32(0.1), jax.random.PRNGKey(rnd)
        )
        if rnd >= rounds - 8:  # score the trained tail, not the warmup
            correct += float(metrics["mc_correct"])
            count += float(metrics["mc_count"])
    acc = correct / max(count, 1.0)
    assert acc > 0.8, f"mc_acc {acc:.3f} not above chance (0.5) margin"


def test_mc_eval_sharded_matches_unsharded():
    """evaluate() over MC candidate batches under a mesh matches the
    unsharded totals (the [B, C, T] eval batch shards its leading axis)."""
    from commefficient_tpu.federated.api import FederatedSession
    from commefficient_tpu.modes.config import ModeConfig
    from commefficient_tpu.parallel import mesh as meshlib

    train, valid, tok = _dataset(num_clients=16, seed=9)
    cfg = dataclasses.replace(
        TINY, vocab_size=tok.vocab_size, n_positions=SEQ, with_mc_head=True
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32), train=False
    )["params"]
    d = ravel_pytree(params)[0].size
    loss = make_lm_mc_loss(model, train=False, mc_coef=1.0, pad_id=tok.pad_id)

    def build(mesh):
        return FederatedSession(
            train_loss_fn=loss, eval_loss_fn=loss, params=params, net_state={},
            mode_cfg=ModeConfig(mode="uncompressed", d=d, momentum_type="none",
                                error_type="none"),
            train_set=train, num_workers=8, local_batch_size=2, seed=1,
            mesh=mesh,
        )

    ref = build(None).evaluate(valid, batch_size=8)
    got = build(meshlib.make_mesh(8)).evaluate(valid, batch_size=8)
    assert ref["mc_count"] > 0
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=1e-5)
