"""Next-utterance classification (double-head) tests — VERDICT r2 #6: the
transfer-learning-conv-ai LM+MC objective the reference inherits (SURVEY.md
§3.2). Packing produces candidate sets with a shuffled gold position; the MC
head scores candidates; federated training drives MC accuracy above chance
on synthetic persona-vs-distractor data within a few rounds."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree

from commefficient_tpu.data.personachat import load_personachat_fed
from commefficient_tpu.federated import engine
from commefficient_tpu.models.gpt2 import TINY, GPT2LMHead
from commefficient_tpu.models.losses import make_lm_mc_loss
from commefficient_tpu.modes.config import ModeConfig

SEQ = 48
C = 2


def _dataset(num_clients=24, seed=3):
    return load_personachat_fed(
        "/nonexistent", num_clients, SEQ, seed, num_candidates=C
    )


def test_mc_packing_shapes_and_labels():
    train, valid, tok = _dataset()
    assert train.num_candidates == C and train.seq_len == SEQ
    rng = np.random.RandomState(0)
    ids = train.sample_clients(rng, 4)
    b = train.client_batch(rng, ids, 2)
    assert b["input_ids"].shape == (4, 2, C, SEQ)
    assert b["token_type_ids"].shape == (4, 2, C, SEQ)
    assert b["labels"].shape == (4, 2, C, SEQ)
    assert b["mc_label"].shape == (4, 2)
    filled = b["mc_label"] >= 0
    assert filled.any()
    # only the gold candidate carries LM labels; distractors are all -100
    for w, n in zip(*np.nonzero(filled)):
        gold = int(b["mc_label"][w, n])
        assert (b["labels"][w, n, gold] != -100).any()
        for c in range(C):
            if c != gold:
                assert (b["labels"][w, n, c] == -100).all()
    # padded rows are ignored by both losses
    for w, n in zip(*np.nonzero(~filled)):
        assert (b["labels"][w, n] == -100).all()


def test_mc_head_output_shapes():
    cfg = dataclasses.replace(TINY, n_positions=SEQ, with_mc_head=True)
    model = GPT2LMHead(cfg)
    ids = jnp.zeros((4, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids, train=False)["params"]
    assert params["mc_head"].shape == (cfg.n_embd,)
    lm, mc = model.apply(
        {"params": params}, ids, train=False,
        mc_positions=jnp.array([5, 0, SEQ - 1, 7]),
    )
    assert lm.shape == (4, SEQ, cfg.vocab_size)
    assert mc.shape == (4,)
    # without positions, same params yield the plain LM path
    lm_only = model.apply({"params": params}, ids, train=False)
    np.testing.assert_allclose(np.asarray(lm_only), np.asarray(lm))


def test_mc_accuracy_rises_above_chance():
    """Joint LM+MC federated training separates gold replies from synthetic
    distractors (reserved-vocabulary marker — see _synthetic) well above the
    1/C chance rate within a few rounds."""
    train, _, tok = _dataset(num_clients=16, seed=5)
    cfg = dataclasses.replace(
        TINY, vocab_size=tok.vocab_size, n_positions=SEQ, with_mc_head=True
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32), train=False
    )["params"]
    d = ravel_pytree(params)[0].size
    mcfg = ModeConfig(mode="uncompressed", d=d, momentum_type="virtual", error_type="none")
    ecfg = engine.EngineConfig(mode=mcfg)
    state = engine.init_server_state(ecfg, params, {})
    loss_fn = make_lm_mc_loss(model, train=True, mc_coef=16.0, pad_id=tok.pad_id)
    step = jax.jit(engine.make_round_step(loss_fn, ecfg))

    rng = np.random.RandomState(7)
    correct = count = 0.0
    rounds = 20
    for rnd in range(rounds):
        ids = train.sample_clients(rng, 8)
        batch = train.client_batch(rng, ids, 4)
        state, _, metrics = step(
            state, batch, {}, jnp.float32(0.1), jax.random.PRNGKey(rnd)
        )
        if rnd >= rounds - 8:  # score the trained tail, not the warmup
            correct += float(metrics["mc_correct"])
            count += float(metrics["mc_count"])
    acc = correct / max(count, 1.0)
    assert acc > 0.8, f"mc_acc {acc:.3f} not above chance (0.5) margin"


def test_mc_eval_sharded_matches_unsharded():
    """evaluate() over MC candidate batches under a mesh matches the
    unsharded totals (the [B, C, T] eval batch shards its leading axis)."""
    from commefficient_tpu.federated.api import FederatedSession
    from commefficient_tpu.modes.config import ModeConfig
    from commefficient_tpu.parallel import mesh as meshlib

    train, valid, tok = _dataset(num_clients=16, seed=9)
    cfg = dataclasses.replace(
        TINY, vocab_size=tok.vocab_size, n_positions=SEQ, with_mc_head=True
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, SEQ), jnp.int32), train=False
    )["params"]
    d = ravel_pytree(params)[0].size
    loss = make_lm_mc_loss(model, train=False, mc_coef=1.0, pad_id=tok.pad_id)

    def build(mesh):
        return FederatedSession(
            train_loss_fn=loss, eval_loss_fn=loss, params=params, net_state={},
            mode_cfg=ModeConfig(mode="uncompressed", d=d, momentum_type="none",
                                error_type="none"),
            train_set=train, num_workers=8, local_batch_size=2, seed=1,
            mesh=mesh,
        )

    ref = build(None).evaluate(valid, batch_size=8)
    got = build(meshlib.make_mesh(8)).evaluate(valid, batch_size=8)
    assert ref["mc_count"] > 0
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=1e-5)


def test_mc_hard_negatives_corpus_structure():
    """--mc_hard_negatives (VERDICT r4 weak #6): distractors come from OTHER
    personas' replies in the SAME word pool, so token identity carries no
    gold-vs-distractor signal; the only learnable signal is matching reply
    words against the persona sentence. Pinned statistically: (a) in the
    easy corpus, distractor rows are dominated by reserved upper-half
    words; in the hard corpus they are not; (b) in the hard corpus, gold
    replies share many more words with their persona sentence than
    distractors do (the matching signal exists)."""
    from commefficient_tpu.data.personachat import _synthetic
    from commefficient_tpu.utils.tokenizer import get_tokenizer

    tok = get_tokenizer()
    words = ["the", "cat", "dog", "runs", "jumps", "likes", "hates", "sees",
             "red", "blue", "big", "small", "fast", "slow", "happy", "sad"]
    upper = set(words[8:])
    # generous seq_len: replies must keep enough words next to the persona
    # for the statistics to be meaningful (the fit() budget in _synthetic
    # guarantees the persona survives packing at ANY seq_len; reply length
    # is whatever budget remains)
    seq_hard = 192

    def stats(hard):
        by_persona, _ = _synthetic(24, seq_hard, tok, seed=3,
                                   num_candidates=C, hard_negatives=hard)
        up_gold, up_distr, gold_overlap, distr_overlap = [], [], [], []
        for seqs in by_persona.values():
            for x, t, y, pos in seqs:
                text = [tok.decode([i for i in row if i != tok.pad_id])
                        for row in x]
                for c in range(C):
                    row_words = text[c].split()
                    # the fit() budget guarantees every candidate row keeps
                    # the full "likes w1..w6" persona prefix — a regression
                    # that truncates it away must fail loudly here, because
                    # it silently destroys the matching signal
                    assert row_words and row_words[0] == "likes", (
                        f"candidate row lost its persona prefix: {text[c]!r}")
                    persona_words = set(row_words[1:7])
                    reply_words = row_words[7:]
                    ups = sum(w in upper for w in reply_words)
                    overlap = sum(w in persona_words for w in reply_words)
                    if c == pos:
                        up_gold.append(ups)
                        gold_overlap.append(overlap)
                    else:
                        up_distr.append(ups)
                        distr_overlap.append(overlap)
        mean = lambda xs: sum(xs) / max(len(xs), 1)
        return (mean(up_gold), mean(up_distr),
                mean(gold_overlap), mean(distr_overlap))

    easy_ug, easy_ud, _, _ = stats(hard=False)
    hard_ug, hard_ud, hard_gold, hard_distr = stats(hard=True)
    # (a) the easy corpus is linearly separable by the reserved upper half
    # (distractor rows dominated by it, gold rows nearly free of it); the
    # hard corpus shows no such vocabulary marker between gold and distractor
    assert easy_ud > 3.0 and easy_ud > 5 * (easy_ug + 0.1), (
        f"easy marker missing: gold {easy_ug:.2f} vs distractor {easy_ud:.2f}")
    assert hard_ud < 1.5 * (hard_ug + 0.1), (
        f"hard corpus still vocab-separable: gold {hard_ug:.2f} "
        f"vs distractor {hard_ud:.2f}")
    # (b) the matching signal: gold replies overlap their persona's words
    # far more than other-persona distractors do
    assert hard_gold > 1.5 * hard_distr, (
        f"no matching signal: gold {hard_gold:.2f} vs distractor {hard_distr:.2f}")
