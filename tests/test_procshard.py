"""Process-sharded ingest (serve/scale/procshard*.py): SO_REUSEPORT worker
processes, shared-memory ring handoff, worker lifecycle.

The acceptance pins live here:

- SERVED == BATCH stays bitwise when the ingest runs as real worker
  PROCESSES — fused AND client-sharded sessions, --serve_fastpath on AND
  off (the shards move bytes and verdicts over shm, never arithmetic);
- admission state is SHARD-OWNED: a retry on the owner is DUPLICATE, a
  kernel-misrouted frame through the shared SO_REUSEPORT port is counted,
  forwarded to the owner, and THEN deduplicated there;
- every exit path unlinks the shm ring segments — normal close, a stop
  with a round still open, and a stop after a SIGKILLed worker leave
  /dev/shm exactly as they found it;
- per-shard counters cross the process boundary into the root's /metrics
  (JSON `shards` block) and /metrics.prom;
- the `shard_kill` fault == a client_drop of the dead shard's client set,
  bitwise, with the casualties re-queued.
"""

from __future__ import annotations

import collections
import json
import os
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
from commefficient_tpu.federated.api import FederatedSession
from commefficient_tpu.modes.config import ModeConfig
from commefficient_tpu.obs import registry as obreg
from commefficient_tpu.resilience import FaultPlan
from commefficient_tpu.serve.ingest import ACCEPTED, DUPLICATE, Submission
from commefficient_tpu.serve.scale.procshard import ProcShardedIngest
from commefficient_tpu.serve.scale.shard import shard_for
from commefficient_tpu.serve.service import AggregationService, ServeConfig
from commefficient_tpu.serve.traffic import TraceConfig, TrafficGenerator
from commefficient_tpu.serve.transport import submit_over_socket

LR = 0.05


# ------------------------------------------------------------------ fixtures


def _quad_loss(params, net_state, batch, rng):
    pred = batch["x"] @ params["w"] + params["b"]
    err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
    mask = batch["mask"]
    count = jnp.maximum(mask.sum(), 1.0)
    per_ex = (err ** 2).sum(-1)
    return (per_ex * mask).sum() / count, {
        "net_state": net_state,
        "metrics": {"loss_sum": (per_ex * mask).sum(), "count": mask.sum()}}


def _tiny_session(clip=0.0, shards=1, seed=0, fault_plan=None):
    rs = np.random.RandomState(0)
    x = rs.randn(96, 6).astype(np.float32)
    w_true = rs.randn(6, 3).astype(np.float32)
    y = (x @ w_true).argmax(-1).astype(np.int32)
    train = FedDataset(x, y, shard_iid(len(x), 12, np.random.RandomState(1)))
    params = {"w": jnp.asarray(rs.randn(6, 3).astype(np.float32) * 0.1),
              "b": jnp.zeros(3)}
    d = ravel_pytree(params)[0].size
    mc = ModeConfig(mode="sketch", d=d, k=4, num_rows=3, num_cols=16,
                    momentum_type="virtual", error_type="virtual")
    return FederatedSession(
        train_loss_fn=_quad_loss, eval_loss_fn=_quad_loss,
        params=params, net_state={}, mode_cfg=mc, train_set=train,
        num_workers=4, local_batch_size=4, seed=seed,
        wire_payloads=True, client_update_clip=clip, client_shards=shards,
        fault_plan=fault_plan,
    )


def _serve(session, rounds, shards=0, shard_mode="thread", fastpath=False,
           quorum=3, trace_seed=5, deadline=4.0, metrics_port=-1,
           on_service=None):
    """Drive served rounds over the real socket wire; shards >= 2 with
    shard_mode="process" runs the SO_REUSEPORT worker-process ingest."""
    cfg = ServeConfig(quorum=quorum, deadline_s=deadline,
                      transport="socket", socket_transport="eventloop",
                      payload="sketch", shards=shards, shard_mode=shard_mode,
                      fastpath=fastpath, metrics_port=metrics_port)
    svc = AggregationService(
        session, cfg,
        traffic=TrafficGenerator(
            TraceConfig(population=session.train_set.num_clients,
                        seed=trace_seed))).start()
    rows = []
    try:
        src = svc.source()
        for _ in range(rounds):
            prep = src.next()
            rows.append(session.commit_round(
                session.dispatch_round(prep, LR))[0])
            src.on_dispatched(session.round - 1)
            src.on_committed(session.round)
        if on_service is not None:
            on_service(svc)
        src.stop()
        with session.mutate_lock:
            rng_state, rng_key = session.rng_snapshot
            session.rng.set_state(rng_state)
            session._rng_key = rng_key
            session._requeue = collections.deque(session._requeue_committed)
            session._requeue_enqueued = dict(
                session._requeue_ages_committed)
    finally:
        svc.close()
    return rows


def _assert_params_equal(sa, sb):
    for x, y in zip(
        jax.tree.leaves(jax.device_get(sa.state["params"])),
        jax.tree.leaves(jax.device_get(sb.state["params"])),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _assert_rows_equal(ra, rb):
    for a, b in zip(ra, rb):
        assert set(a) == set(b)
        for k in a:
            assert a[k] == b[k], (k, a[k], b[k])


def _shm_names():
    try:
        return set(os.listdir("/dev/shm"))
    except FileNotFoundError:  # non-tmpfs platform: nothing to pin
        return set()


# --------------------------- THE pin: process shards == fused, bitwise


@pytest.mark.parametrize("fastpath,session_shards", [
    (False, 1),
    (True, 1),
    (True, 2),   # client-sharded session under the process-shard ingest
])
def test_proc_sharded_serving_equals_fused_bitwise(fastpath, session_shards):
    """THE acceptance pin: serving through N SO_REUSEPORT worker
    PROCESSES (shm ring handoff, fastpath on and off) is bit-identical —
    params + every logged row — to the fused single-listener socket path
    of the same session."""
    sa = _tiny_session(shards=session_shards)
    ra = _serve(sa, 3, shards=2, shard_mode="process", fastpath=fastpath)
    sb = _tiny_session(shards=session_shards)
    rb = _serve(sb, 3)
    _assert_params_equal(sa, sb)
    _assert_rows_equal(ra, rb)


def test_proc_shards_equal_thread_shards_bitwise():
    """Process shards and thread shards are the same admission machine:
    identical params + rows for the same session/trace."""
    sa = _tiny_session()
    ra = _serve(sa, 3, shards=2, shard_mode="process")
    sb = _tiny_session()
    rb = _serve(sb, 3, shards=2, shard_mode="thread")
    _assert_params_equal(sa, sb)
    _assert_rows_equal(ra, rb)


# ------------------------------------------- shard-owned admission state


def test_shard_owned_dedup_and_misroute_forwarding():
    """Admission state is shard-OWNED: a retry on the owner's direct port
    is DUPLICATE; frames through the shared SO_REUSEPORT port get
    kernel-spread (misroutes counted + forwarded to the owner) and STILL
    deduplicate, because the verdict comes from the one owner."""
    t = ProcShardedIngest(n_shards=2)
    try:
        t.start()
        ids = list(range(100, 148))
        t.queue.open_round(0, ids)
        # owner-routed: accept once, DUPLICATE on retry
        assert t.submit(Submission(client_id=100, round=0,
                                   latency_s=0.1)) == ACCEPTED
        assert t.submit(Submission(client_id=100, round=0,
                                   latency_s=0.1)) == DUPLICATE
        # shared port: the kernel spreads conns by 4-tuple hash, blind to
        # client ownership — with 32 submissions over 2 shards the odds of
        # zero misroutes are 2^-32. All must come back ACCEPTED (forwarded
        # to the owner), retries all DUPLICATE (owner state, not local).
        shared = t.address
        for cid in ids[1:33]:
            assert submit_over_socket(
                shared, Submission(client_id=cid, round=0,
                                   latency_s=0.1)) == ACCEPTED
        for cid in ids[1:33]:
            assert submit_over_socket(
                shared, Submission(client_id=cid, round=0,
                                   latency_s=0.1)) == DUPLICATE
        shards = t.counters()
        assert sum(s["misrouted"] for s in shards.values()) > 0
        merged = t.queue.close_round(0)
        assert sorted(a.client_id for a in merged) == ids[:33]
        # recv_order residues are disjoint per shard (globalization)
        assert len({a.recv_order for a in merged}) == len(merged)
    finally:
        t.stop()


def test_shard_for_partitions_every_client():
    ids = np.arange(5000, 5200)
    owners = {int(cid): shard_for(int(cid), 4) for cid in ids}
    assert set(owners.values()) <= set(range(4))
    assert len(set(owners.values())) > 1
    # stable: the same id always lands on the same shard
    for cid in ids[:20]:
        assert shard_for(int(cid), 4) == owners[int(cid)]


# ------------------------------------------------ shm ring segment hygiene


def test_shm_ring_cleanup_on_every_exit_path():
    """No leaked /dev/shm segments: normal stop, stop with a round still
    open (armed blocks), and stop after a SIGKILLed worker all unlink
    every ring segment the root created."""
    before = _shm_names()

    # normal open/close/stop
    t = ProcShardedIngest(n_shards=2, payload_shape=(3, 16), fastpath=True)
    t.start()
    t.queue.open_round(0, list(range(12)))
    t.queue.close_round(0)
    t.stop()
    assert _shm_names() <= before

    # stop with the round still open (blocks armed, never closed)
    t = ProcShardedIngest(n_shards=2, payload_shape=(3, 16), fastpath=True)
    t.start()
    t.queue.open_round(0, list(range(12)))
    t.stop()
    assert _shm_names() <= before

    # a worker SIGKILLed mid-round (its mapping dies with it; the root
    # still owns + unlinks the segment)
    t = ProcShardedIngest(n_shards=2, payload_shape=(3, 16), fastpath=True)
    t.start()
    t.queue.open_round(0, list(range(12)))
    t.kill_shard(1)
    t.queue.close_round(0)
    t.stop()
    assert _shm_names() <= before


def test_dead_worker_respawns_at_next_open():
    t = ProcShardedIngest(n_shards=2)
    try:
        t.start()
        pid0 = t.workers[1].proc.pid
        t.queue.open_round(0, list(range(8)))
        t.kill_shard(1)
        assert not t.workers[1].alive
        t.queue.close_round(0)
        # next open respawns: fresh process, fresh (empty) admission state
        t.queue.open_round(1, list(range(8)))
        assert t.workers[1].alive
        assert t.workers[1].proc.pid != pid0
        assert t.submit(Submission(client_id=1, round=1,
                                   latency_s=0.1)) == ACCEPTED
        t.queue.close_round(1)
    finally:
        t.stop()


# -------------------------------------------- cross-process observability


def test_cross_process_counters_aggregate_into_metrics():
    """Worker-side counters cross the process boundary: the /metrics JSON
    `shards` block carries per-shard liveness + totals, the queue
    counters sum across shards, and /metrics.prom renders the per-shard
    series from the root registry."""
    captured = {}

    def grab(svc):
        host, port = svc.metrics_server.address
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=5) as r:
            captured["json"] = json.loads(r.read())
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics.prom", timeout=5) as r:
            captured["prom"] = r.read().decode()

    session = _tiny_session()
    _serve(session, 2, shards=2, shard_mode="process", metrics_port=0,
           on_service=grab)
    snap = captured["json"]
    assert snap["shard_mode"] == "process"
    shards = snap["shards"]
    assert set(shards) == {"0", "1"}
    for s in shards.values():
        assert s["alive"] and s["pid"]
    # every admitted submission was counted by exactly one worker
    assert snap["submissions"]["accepted"] > 0
    assert sum(s["submissions"] for s in shards.values()) \
        >= snap["submissions"]["accepted"]
    assert "serve_shard0_submissions_total" in captured["prom"]
    assert "serve_shard1_submissions_total" in captured["prom"]


# ----------------------------- worker lifecycle: shard_kill == client_drop


def test_shard_kill_equals_client_drop_bitwise():
    """A SIGKILLed shard worker mid-run == a client_drop of its whole
    hash-shard (same positions, same round), bitwise, and the casualties
    go through the requeue machinery. Deaths are counted."""
    N, kill_round, dead = 2, 1, 1
    plan = FaultPlan.parse(f"shard_kill@{kill_round}:shards={dead}")
    sa = _tiny_session(fault_plan=plan)
    # the doomed set the ownership hash will pick: this round's cohort is
    # a pure function of the session's sampling stream
    probe = _tiny_session()
    ids = [probe.sample_cohort(r) for r in range(kill_round + 1)][-1]
    doomed = [p for p, cid in enumerate(ids)
              if shard_for(int(cid), N) == dead]
    assert doomed, "hash assignment left the dead shard empty"
    plan_b = FaultPlan.parse(
        f"client_drop@{kill_round}:clients="
        + "+".join(str(p) for p in doomed))
    sb = _tiny_session(fault_plan=plan_b)
    snap0 = obreg.default().snapshot()
    ra = _serve(sa, 3, shards=N, shard_mode="process", quorum=0)
    snap1 = obreg.default().snapshot()
    rb = _serve(sb, 3, shards=N, shard_mode="process", quorum=0)
    _assert_params_equal(sa, sb)
    _assert_rows_equal(ra, rb)
    assert ra[kill_round]["clients_dropped"] >= len(doomed)
    assert ra[kill_round]["requeue_depth"] >= len(doomed)
    assert snap1.get("serve_shard_deaths_total", 0) \
        > snap0.get("serve_shard_deaths_total", 0)
    assert snap1.get("resilience_fault_shard_kill_total", 0) \
        > snap0.get("resilience_fault_shard_kill_total", 0)


# --------------------------------------------------- config + plan guards


def test_process_mode_rejections():
    base = dict(quorum=3, deadline_s=4.0, transport="socket",
                socket_transport="eventloop", payload="sketch")
    session = _tiny_session()
    for bad in (
        dict(shards=2, shard_mode="process", async_mode=True),
        dict(shards=2, shard_mode="process", pipeline=True),
        dict(shards=2, shard_mode="process", edges=2),
        dict(shards=0, shard_mode="process"),
    ):
        with pytest.raises(ValueError, match="shard_mode|serve_shards"):
            AggregationService(session, ServeConfig(**base, **bad))
    with pytest.raises(ValueError, match="n_shards"):
        ProcShardedIngest(n_shards=1)


def test_shard_kill_plan_validation():
    plan = FaultPlan.parse("shard_kill@1:shards=1+3")
    assert plan.has_shard_kill()
    # vacuous: shard_kill without a process-sharded serve
    with pytest.raises(ValueError, match="never fire"):
        plan.validate_shard_context(False, 0)
    # out-of-range shard index
    with pytest.raises(ValueError, match="never fire"):
        plan.validate_shard_context(True, 2)
    plan.validate_shard_context(True, 4)
    assert plan.shard_kill_plan(1) == (1, 3)
    assert plan.shard_kill_plan(0) == ()
    with pytest.raises(ValueError, match="shards="):
        FaultPlan.parse("shard_kill@1")  # needs shards=
