"""Conforming twin: the handler sets an Event, emits through the
signal-safe tracer entry, and writes to stderr — the PR 7 discipline.
"""

import signal
import sys
import threading

_DRAIN = threading.Event()


class _Trace:
    def instant_signal_safe(self, *args, **kwargs):
        pass


_TRACER = _Trace()


def _on_term(signum, frame):
    del frame
    _DRAIN.set()
    _TRACER.instant_signal_safe("term", signum=signum)
    print("terminating", file=sys.stderr)


def install():
    signal.signal(signal.SIGTERM, _on_term)
