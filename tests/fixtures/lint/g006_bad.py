# graftlint: module=commefficient_tpu/federated/fake_noise.py
# G006 violating twin: one key feeds two consumers (correlated streams).
import jax


def sample_batch(shape):
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, shape)
    y = jax.random.uniform(key, shape)  # reuse: correlated with x
    return x, y


def per_step(key, xs):
    out = []
    for x in xs:
        # loop-invariant key: every iteration draws the same stream
        out.append(jax.random.normal(key, x.shape))
    return out
