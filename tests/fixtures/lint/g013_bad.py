# graftlint: module=commefficient_tpu/federated/engine.py
# G013 violating twin: arithmetic over the stale wire stack OUTSIDE the
# declared staleness-fold boundary — a second, undeclared fold site whose
# order and weight handling are pinned nowhere (the async==sync bit-
# identity rests on there being exactly one), plus a second declared
# boundary hiding under the first's exemption.
import jax
import jax.numpy as jnp


# graftlint: staleness-fold — the declared fold site
def _stale_fold(table, live, stale_tables, stale_weights):
    def body(carry, xs):
        tbl, w = carry
        t, wt = xs
        return (tbl + wt * t, w + wt), None

    (folded, total), _ = jax.lax.scan(
        body, (table, live), (stale_tables, stale_weights))
    return folded, total


def sneaky_inline_fold(table, stale_tables, stale_weights):
    # undeclared second fold: a dense einsum reassociates the slot order
    return table + jnp.einsum("s,src->rc", stale_weights, stale_tables)


# graftlint: staleness-fold — a SECOND declared boundary (itself illegal)
def another_fold(table, stale_tables, stale_weights):
    return table + (stale_weights[:, None, None] * stale_tables).sum(0)
