# graftlint: module=commefficient_tpu/federated/api.py
# G014 conforming twin: ONE declared ledger-commit boundary owns the
# append; everything else only builds the writer (config wiring) or hands
# committed records to the boundary.
from commefficient_tpu.obs import ledger as obledger


def attach_ledger(session, path, resume_round):
    # constructing the writer is wiring, not an append
    session.ledger = obledger.RoundLedger(path, resume_round=resume_round)
    return session.ledger


# graftlint: ledger-commit — THE declared append site (commit boundary)
def _publish_round_obs(session, records):
    for rnd, ids, m, health, fp in records:
        session.ledger.append_round(
            rnd, cohort=ids, metrics=m, health=health, fingerprint=fp)


def commit_rounds(session, infls, metrics_hosts):
    records = [(0, [1, 2], m, None, None) for m in metrics_hosts]
    _publish_round_obs(session, records)
    return records
