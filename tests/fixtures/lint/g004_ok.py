# graftlint: module=commefficient_tpu/resilience/fake_saver.py
# G004 conforming twin: reads are fine, writes go through the atomic helper.
from ..utils import checkpoint as ckpt


def save_state(ckpt_dir, session):
    return ckpt.save(ckpt_dir, session)


def read_meta(ckpt_dir):
    with open(ckpt_dir + "/meta.json") as f:  # read mode: legal
        return f.read()
