# graftlint: module=commefficient_tpu/modes/fake_merge.py
# G002 violating twin: unordered cross-device reduction in parity scope.
from jax import lax


def merge_partial_tables(tables, axis_names):
    # a ring psum reassociates the fp sum per topology: parity breaks
    return lax.psum(tables, axis_names)
