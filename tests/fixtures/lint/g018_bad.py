"""Violating fixture: a two-lock acquisition cycle, half interprocedural.

`fill_slot` nests the ring lock inside the slot lock; `flush_ring` holds
the ring lock and calls a helper that takes the slot lock — the classic
inversion, invisible to a purely lexical scan.
"""
# graftlint: module=commefficient_tpu/serve/scale/ringlocks_demo.py

import threading

_SLOT_LOCK = threading.Lock()
_RING_LOCK = threading.Lock()


def fill_slot():
    with _SLOT_LOCK:
        with _RING_LOCK:
            return 1


def _grab_slot():
    with _SLOT_LOCK:
        return 2


def flush_ring():
    with _RING_LOCK:
        return _grab_slot()
