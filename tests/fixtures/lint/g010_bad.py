# graftlint: module=commefficient_tpu/federated/engine.py
# G010 violating twin: an UNDECLARED ravel_pytree in the round-path compiled
# scope — a casually-added flat [d] materialization that re-introduces the
# HBM ceiling the layerwise sketch path exists to remove.
from jax.flatten_util import ravel_pytree


def make_round_step(cfg):
    def round_step(state, batch):
        grads = batch["grads"]  # per-leaf pytree off the backward pass
        gflat, _ = ravel_pytree(grads)  # the dense [d] vector, undeclared
        return state, gflat * 0.1

    return round_step
