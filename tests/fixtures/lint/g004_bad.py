# graftlint: module=commefficient_tpu/resilience/fake_saver.py
# G004 violating twin: raw writes into a checkpoint dir, no staging/manifest.
import json
import os
import pickle

import numpy as np


def save_state(ckpt_dir, state, meta):
    np.save(os.path.join(ckpt_dir, "state.npy"), state)
    with open(os.path.join(ckpt_dir, "meta.json"), "w") as f:
        json.dump(meta, f)
    with open(ckpt_dir + "/rng.pkl", "wb") as fh:
        pickle.dump(meta, fh)
