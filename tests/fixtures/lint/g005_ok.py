# graftlint: module=commefficient_tpu/federated/fake_session.py
# G005 conforming twin: the canonical donation idiom rebinds the name, and
# only the returned state is read afterwards.
import jax


def body(state, batch):
    return state


def run(state, batch):
    step = jax.jit(body, donate_argnums=(0,))
    state = step(state, batch)  # rebind: the old buffer has no readers
    return state["params"], state
