# graftlint: module=commefficient_tpu/runner/fake_loop.py
# G007 conforming twin: the dispatch path blocks on a condition variable
# owned by the worker thread; the sleep lives on the writer thread, which
# is not reachable from run_loop.
import time


def _writer_thread(writer):
    while writer.alive:
        time.sleep(0.5)  # not reachable from the dispatch roots
        writer.flush()


def run_loop(session, cfg):
    for _ in range(cfg.total_rounds):
        session.dispatch()
