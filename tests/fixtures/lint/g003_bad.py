# graftlint: module=commefficient_tpu/federated/fake_step.py
# G003 violating twin: direct reads of the reserved `_valid` batch leaf.
VALID_KEY = "_valid"


def step(state, batch):
    valid = batch["_valid"]          # direct subscript read
    fallback = batch.get("_valid")   # .get read
    return valid, fallback


def step_symbolic(state, batch):
    return batch[VALID_KEY]          # symbolic read is the same violation
