"""Violating fixture: an attribute mutated from the reactor thread AND
the caller's thread with no common lock — the race G019 exists to catch.
"""
# graftlint: module=commefficient_tpu/serve/scale/reactor_demo.py

import threading


class Reactor:
    def __init__(self):
        self._lock = threading.Lock()
        self._inflight = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit(self, item):
        self._inflight += 1  # caller thread, unlocked
        return item

    def _loop(self):
        while True:
            self._inflight -= 1  # reactor thread, unlocked
