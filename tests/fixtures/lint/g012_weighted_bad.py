# graftlint: module=commefficient_tpu/federated/engine.py
# G012 violating twin, weighted-order-statistics form: a "weighted median"
# over the stale union stack smuggled INTO the staleness-fold boundary.
# The staleness-fold declaration sanctions the LINEAR slot-ordered scan
# only — order statistics over stale wires belong in the robust-merge
# boundary (modes._robust_table_merge's union-stack form), so every sort/
# searchsorted here must fire G012 even though the function is a declared
# G013 boundary (the wrong boundary's exemption buys nothing).
import jax.numpy as jnp


# graftlint: staleness-fold — the declared (linear!) fold site
def _stale_fold(table, live_weight, stale_tables, stale_weights):
    # a weighted median hiding behind the stale-fold declaration: sorts
    # and rank machinery over the stale union stack — an undeclared
    # second robust-merge semantics
    union = jnp.concatenate([table[None], stale_tables], axis=0)
    order = jnp.argsort(union, axis=0, stable=True)
    sw = jnp.take_along_axis(
        jnp.broadcast_to(stale_weights[:, None, None], union.shape),
        order, axis=0)
    cum = jnp.cumsum(sw, axis=0)
    lo = jnp.searchsorted(cum[:, 0, 0], stale_weights.sum() / 2.0)
    return jnp.take(jnp.sort(union, axis=0), lo, axis=0), live_weight
