"""Conforming twin: the same two locks, one global order everywhere —
and the order is DECLARED with lock-order names (l0- sorts before l1-),
so the nesting edge is sanctioned, not merely cycle-free by luck.
"""
# graftlint: module=commefficient_tpu/serve/scale/ringlocks_demo_ok.py

import threading

# graftlint: lock-order l0-slot
_SLOT_LOCK = threading.Lock()
# graftlint: lock-order l1-ring
_RING_LOCK = threading.Lock()


def fill_slot():
    with _SLOT_LOCK:
        with _RING_LOCK:
            return 1


def _grab_ring():
    with _RING_LOCK:
        return 2


def flush_ring():
    with _SLOT_LOCK:
        return _grab_ring()
