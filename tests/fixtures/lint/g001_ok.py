# graftlint: module=commefficient_tpu/federated/fake_dispatch.py
# G001 conforming twin: dispatch defers, the declared drain point syncs.
import jax


def dispatch_round(session, infl):
    # no host sync: metrics stay device arrays until the drain boundary
    return infl.metrics


# graftlint: drain-point — the sanctioned batched sync
def drain(pending):
    return jax.device_get([fl.metrics for fl in pending])
