# graftlint: module=commefficient_tpu/federated/engine.py
# G012/G013 conforming twin, weighted-order-statistics form: the merge
# FORWARDS the stale union stacks into the robust-merge boundary (an
# attribute call through modes.merge_partial_wires — the per-buffer
# robust merge), and the declared staleness-fold stays strictly linear.
# No order statistic, and no stale arithmetic, lives outside a boundary.
import jax

from commefficient_tpu.modes import modes


# graftlint: staleness-fold — THE declared (linear) fold site
def _stale_fold(table, live_weight, stale_tables, stale_weights):
    def body(carry, xs):
        tbl, wsum = carry
        t, w = xs
        return (tbl + w * t, wsum + w), None

    (folded, total), _ = jax.lax.scan(
        body, (table, live_weight), (stale_tables, stale_weights))
    return folded, total


def merge_step(mcfg, tables, part_eff, trim,
               stale_tables=None, stale_weights=None):
    # the per-buffer robust merge: bare keyword FORWARDING of the stale
    # stacks into the ONE robust-merge boundary — the sanctioned shape
    robust, total_w, extras = modes.merge_partial_wires(
        mcfg, {"table": tables}, policy="trimmed", live=part_eff,
        trim=trim, stale_tables=stale_tables, stale_weights=stale_weights,
        want_residual=True)
    return robust, total_w, extras
