# graftlint: module=commefficient_tpu/federated/fake_dispatch.py
# G001 violating twin: host syncs on the round path, no drain point.
import jax


def dispatch_round(session, infl):
    # hidden sync: blocks the dispatch thread on device completion
    metrics = jax.device_get(infl.metrics)
    # hidden sync: .item() forces a device round-trip per scalar
    loss = infl.loss.item()
    return metrics, loss
