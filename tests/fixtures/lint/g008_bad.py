# graftlint: module=commefficient_tpu/runner/fake_config.py
# G008 violating twin: flags read in runner code that were never registered
# through utils/config.py (typo'd and smuggled).
def from_args(args):
    return {
        "turbo": args.turbo_mode,                 # unregistered flag
        "depth": getattr(args, "pipeline_depthh", 0),  # typo'd getattr
    }
