# graftlint: module=commefficient_tpu/federated/api.py
# G014 violating twin: ledger appends OUTSIDE the declared commit
# boundary — a prepare path writing optimistically (the round may never
# commit; the rewind would take it back and the file would lie), plus a
# SECOND declared boundary hiding under the first's exemption.
from commefficient_tpu.obs import ledger as obledger


# graftlint: ledger-commit — the declared append site
def _publish_round_obs(session, records):
    for rnd, m in records:
        session.ledger.append_round(rnd, metrics=m)


def prepare_round(session, rnd):
    batch = {"x": None}
    # optimistic append at PREPARE time: this round is not committed —
    # prefetch may rewind it and the ledger would carry a phantom round
    session.ledger.append_round(rnd, metrics={})
    return batch


def flush_tail(session, pending):
    writer = obledger.RoundLedger("/tmp/l.jsonl")  # construction is legal
    for rnd in pending:
        # "flushing" uncommitted rounds on exit: the exact bug class
        writer.append_round(rnd)


# graftlint: ledger-commit — a SECOND declared boundary (itself illegal)
def another_writer(session, rnd, m):
    session.ledger.append_round(rnd, metrics=m)
