"""Violating fixture for the G001 taint pass: float() on a value derived
from a traced parameter, smuggled one helper call deep. The pre-taint
syntactic rule provably misses this (see the regression test that runs
it with taint_pass disabled).
"""
# graftlint: module=commefficient_tpu/modes/taint_demo.py

from .g001_taint_helper import coerce_scale


def merge_round(table, scale):
    s = scale * 2
    return table, coerce_scale(s)
