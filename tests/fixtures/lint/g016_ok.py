# graftlint: module=commefficient_tpu/serve/ring.py
# G016 conforming twin: the ONE sanctioned per-submission copy — the
# write into the pinned ring slot — is declared `# graftlint: ring-write`
# on its def; everything else in fast-path scope moves views, not bytes.
import numpy as np


class RingSlot:
    def __init__(self, block, index):
        self.block = block
        self.index = index

    # graftlint: ring-write — the one sanctioned per-submission copy
    def write(self, table):
        self.block.tables[self.index][...] = table
        return self.block.tables[self.index]


def block_view(block, lo, hi):
    # contiguous ring view: no bytes move
    return block.tables[lo:hi]


def finite_mask(chunk):
    # vectorized screen over a stacked VIEW — reductions, not copies
    return np.isfinite(chunk).all(axis=(1, 2))
