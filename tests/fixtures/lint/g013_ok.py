# graftlint: module=commefficient_tpu/federated/engine.py
# G013 conforming twin: the ONE declared staleness-fold boundary owns every
# touch of the stale wire stack; the merge only FORWARDS the stack to it.
import jax


# graftlint: staleness-fold — THE declared fold site
def _stale_fold(table, live, stale_tables, stale_weights):
    def body(carry, xs):
        tbl, w = carry
        t, wt = xs
        return (tbl + wt * t, w + wt), None

    (folded, total), _ = jax.lax.scan(
        body, (table, live), (stale_tables, stale_weights))
    return folded, total, {"stale_weight": stale_weights.sum()}


def merge_step(state, tables, live, stale_tables=None, stale_weights=None):
    table = tables.sum(axis=0)
    # bare forwarding to the boundary: the one legal shape outside it
    folded, total, metrics = _stale_fold(
        table, live, stale_tables, stale_weights)
    return folded / total, metrics
