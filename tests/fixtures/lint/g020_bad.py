"""Violating fixture: a SIGTERM handler that takes a non-reentrant lock
and opens a file — both deadlock/corruption hazards in signal context.
"""

import signal
import threading

_STATE_LOCK = threading.Lock()


def _on_term(signum, frame):
    del frame
    with _STATE_LOCK:
        with open("/tmp/last_signal.txt", "w") as f:
            f.write(str(signum))


def install():
    signal.signal(signal.SIGTERM, _on_term)
