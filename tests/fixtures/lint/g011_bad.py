# graftlint: module=commefficient_tpu/serve/ingest.py
# G011 violating twin: wire frame bytes decoded OUTSIDE the declared
# payload boundary, and a raw `.payload` field fed straight into compiled
# scope — both reopen the injection classes the validation gauntlet closes.
import base64

import jax.numpy as jnp
import numpy as np


def sneak_decode(frame):
    # undeclared deserialization of untrusted transport input
    raw = base64.b64decode(frame["data"])
    return np.frombuffer(raw, dtype="<f4")


def sneak_merge(state, sub):
    # the frame field flows into compiled scope without the gauntlet
    return state + jnp.asarray(sub.payload)
