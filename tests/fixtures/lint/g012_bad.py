# graftlint: module=commefficient_tpu/modes/modes.py
# G012 violating twin: order statistics over the client-stacked tables
# OUTSIDE the declared robust-merge boundary — an undeclared second
# aggregation semantics (its tie-breaks and fp association are pinned
# nowhere), plus a screening percentile in parity scope.
import jax.numpy as jnp


def sneaky_median_merge(tables, live):
    # undeclared coordinate-wise median over the [W, r, c] client stack
    keyed = jnp.where(live[:, None, None] > 0, tables, jnp.inf)
    return jnp.sort(keyed, axis=0)[tables.shape[0] // 2]


def sneaky_trim(tables):
    # undeclared trimming via percentile thresholds
    hi = jnp.percentile(tables, 90.0, axis=0)
    return jnp.where(tables > hi[None], 0.0, tables).sum(axis=0)
