# graftlint: module=commefficient_tpu/serve/scale/fake_reactor.py
# G015 violating twin: a blocking sleep AND a raw socket recv reachable
# from the reactor's dispatch loop (_loop -> _backoff / _read_now) — one
# blocked reactor is every connection blocked at once.
import time


def _backoff():
    time.sleep(0.1)


def _read_now(conn):
    return conn.recv(65536)  # raw socket op outside any declared seam


def _loop(self):
    while not self.stop:
        _backoff()
        for conn in self.conns:
            _read_now(conn)
