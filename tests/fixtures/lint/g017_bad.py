# graftlint: module=commefficient_tpu/serve/scale/procshard_worker.py
# G017 violating twin: two fork-unsafe imports in a worker-entry module —
# a direct module-level jax import (the spawned shard worker would
# initialize the accelerator runtime per shard), and one smuggled behind
# a same-directory helper import the module-local view cannot see.
import json

import jax.numpy as jnp  # direct: module-level jax in the worker chain
import numpy as np

from .g017_helper_bad import device_merge  # transitive: helper imports jax


def worker_main(cfg, ctl):
    table = np.zeros((cfg["rows"], cfg["cols"]), np.float32)
    ctl.send(("ready", json.dumps({"ok": True})))
    return device_merge(jnp.asarray(table))
