# graftlint: module=commefficient_tpu/federated/fake_noise.py
# G006 conforming twin: split first, one consumer per key; fold_in with
# distinct ints is derivation, not consumption.
import jax


def sample_batch(shape):
    kx, ky = jax.random.split(jax.random.PRNGKey(0))
    x = jax.random.normal(kx, shape)
    y = jax.random.uniform(ky, shape)
    return x, y


def per_item(key, xs):
    return [jax.random.normal(jax.random.fold_in(key, i), x.shape)
            for i, x in enumerate(xs)]
