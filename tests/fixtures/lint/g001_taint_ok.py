"""Conforming twin: the helper coerces a module constant and shape
metadata — taint does not flow through `.shape` (static metadata is
host-safe even on a traced array).
"""
# graftlint: module=commefficient_tpu/modes/taint_demo_ok.py

from .g001_taint_helper import coerce_scale

_BASE = 3.0


def merge_round(table, scale):
    del scale
    n = coerce_scale(_BASE)
    m = coerce_scale(table.shape[0])
    return table, n + m
