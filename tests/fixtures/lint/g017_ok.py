# graftlint: module=commefficient_tpu/serve/scale/procshard_worker.py
# G017 conforming twin: the worker-entry chain is numpy/stdlib-only at
# module level. Device-touching work stays behind a FUNCTION-LOCAL import
# in a root-only code path — the sanctioned lazy shape (PEP 562
# __getattr__ bodies are the same exemption).
import json
import selectors
import socket

import numpy as np


def worker_main(cfg, ctl):
    table = np.zeros((cfg["rows"], cfg["cols"]), np.float32)
    ctl.send(("ready", json.dumps({"ok": True})))
    return table, selectors.DefaultSelector(), socket.AF_INET


def root_only_upload(stack):
    # lazy: only the ROOT process ever calls this; the worker never
    # executes the import
    import jax.numpy as jnp

    return jnp.asarray(stack)
