# graftlint: module=commefficient_tpu/serve/scale/fake_reactor.py
# G015 conforming twin: the loop waits ONLY in the declared selector seam
# and touches sockets only through declared non-blocking I/O helpers; the
# sleep lives on an unrelated client helper no loop root reaches.
import time


# graftlint: drain-point — the reactor's one sanctioned wait
def _select(self, timeout):
    return self.sel.select(timeout)


# graftlint: drain-point — non-blocking recv; would-block falls back
def _on_readable(self, conn):
    return conn.sock.recv(65536)


def _loop(self):
    while not self.stop:
        for key, _ in _select(self, 0.5):
            _on_readable(self, key.data)


def client_backoff_helper():
    time.sleep(0.1)  # client-side thread: not reachable from _loop
