# graftlint: module=commefficient_tpu/runner/fake_helper.py
# Helper module for the G007 package-level fixtures: the blocking sleep a
# module-local call graph cannot see from the importing loop.
import time


def wait_ready(session):
    while not session.ready:
        time.sleep(0.5)
