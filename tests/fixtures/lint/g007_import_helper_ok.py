# graftlint: module=commefficient_tpu/runner/fake_helper_ok.py
# Conforming helper twin: the same blocking wait, but DECLARED as the
# sanctioned boundary — package-level G007 stops at a drain-point.
import time


# graftlint: drain-point — the sanctioned serving-queue wait
def wait_ready(session):
    while not session.ready:
        time.sleep(0.5)
