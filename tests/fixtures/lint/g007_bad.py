# graftlint: module=commefficient_tpu/runner/fake_loop.py
# G007 violating twin: a blocking sleep reachable from the dispatch path
# (run_loop -> _poll_ready -> time.sleep).
import time


def _poll_ready(session):
    while not session.ready:
        time.sleep(0.5)


def run_loop(session, cfg):
    for _ in range(cfg.total_rounds):
        _poll_ready(session)
        session.dispatch()
