# graftlint: module=commefficient_tpu/federated/engine.py
# G010 conforming twin: the ravel path's declared flat boundary (the def
# carries `# graftlint: sketch-boundary`) stays legal — the rule bans
# UNDECLARED flat materialization, not the ravel path itself — and the
# layerwise branch never ravels at all.
from jax.flatten_util import ravel_pytree  # the import alone moves no bytes


# graftlint: sketch-boundary — the ravel path IS the declared flat boundary
def make_ravel_round_step(cfg):
    def round_step(state, batch):
        gflat, _ = ravel_pytree(batch["grads"])
        return state, gflat * 0.1

    return round_step


def make_layerwise_round_step(cfg, sketch_tree, plan):
    def round_step(state, batch):
        # per-leaf accumulation: the flat vector never exists
        table = sketch_tree(cfg.sketch_spec, batch["grads"], plan)
        return state, table

    return round_step
