# graftlint: module=commefficient_tpu/federated/fake_session.py
# G005 violating twin: the donated input is read after the jitted call.
import jax


def body(state, batch):
    return state


def run(state, batch):
    step = jax.jit(body, donate_argnums=(0,))
    new_state = step(state, batch)
    return state["params"], new_state  # `state`'s buffer is deleted on TPU
