# graftlint: module=commefficient_tpu/modes/modes.py
# G012 conforming twin: the ONE declared robust-merge boundary owns every
# order statistic; the caller dispatches into it and otherwise merges by
# the ordered sum (the parity-pinned association).
import jax.numpy as jnp


# graftlint: robust-merge — the declared order-statistics site
def _robust_table_merge(stacked, live, policy, trim):
    keyed = jnp.where(live.reshape((-1, 1, 1)) > 0, stacked, jnp.inf)
    order = jnp.argsort(keyed, axis=0, stable=True)
    ranks = jnp.argsort(order, axis=0, stable=True)
    n = live.sum().astype(jnp.int32)
    keep = (ranks >= trim) & (ranks < n - trim)
    return jnp.where(keep, stacked, 0.0).sum(axis=0)


def merge_partial_wires(stacked, live=None, policy="sum", trim=0):
    if policy != "sum":
        return _robust_table_merge(stacked, live, policy, trim)
    # the linear ordered sum: no order statistics anywhere near it
    return stacked.sum(axis=0)
