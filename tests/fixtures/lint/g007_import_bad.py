# graftlint: module=commefficient_tpu/runner/fake_loop2.py
# G007 package-level violating twin: the sleep is smuggled behind a helper
# IMPORT (run_loop -> wait_ready in another module) — the case the
# module-local reachability used to miss.
from .g007_import_helper_bad import wait_ready


def run_loop(session, cfg):
    for _ in range(cfg.total_rounds):
        wait_ready(session)
        session.dispatch()
