# graftlint: module=commefficient_tpu/runner/fake_config.py
# G008 conforming twin: every read is a flag utils/config.py registers.
def from_args(args):
    return {
        "checkpoint_every": args.checkpoint_every,
        "sync_loop": args.sync_loop,
        "depth": getattr(args, "prefetch_depth", 0),
    }
