# graftlint: module=commefficient_tpu/federated/fake_step.py
# G003 conforming twin: the mask is INSTALLED by assignment (legal: that is
# the injection side) and CONSUMED only via split_valid.
VALID_KEY = "_valid"


def split_valid(batch):
    if isinstance(batch, dict) and VALID_KEY in batch:
        batch = dict(batch)
        return batch, batch.pop(VALID_KEY)
    return batch, None


def prepare(batch, valid):
    batch = dict(batch)
    batch[VALID_KEY] = valid  # Store context: installing the mask is legal
    return batch


def step(state, batch):
    batch, valid = split_valid(batch)
    return state, valid
