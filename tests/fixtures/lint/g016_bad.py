# graftlint: module=commefficient_tpu/serve/gauntlet.py
# G016 violating twin: three per-submission byte-touching moves in
# fast-path scope — a base64 decode on the hot loop, a "defensive"
# frombuffer().copy(), and the old per-round np.stack the pinned ring
# exists to replace. Each one silently doubles bytes-touched-per-table
# without failing any bitwise test.
import base64

import numpy as np


def decode_in_gauntlet(frame):
    # frame decoding belongs to validate_payload, not the gauntlet loop
    return base64.b64decode(frame["data"])


def defensive_copy(raw):
    # duplicates freshly decoded frame bytes per submission
    return np.frombuffer(raw, dtype="<f4").copy()


def restack_block(tables):
    # the slow path's per-round stack copy sneaking back in
    return np.stack(tables, axis=0)
