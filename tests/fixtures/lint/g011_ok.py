# graftlint: module=commefficient_tpu/serve/ingest.py
# G011 conforming twin: the declared payload boundary (the def carries
# `# graftlint: payload-boundary`) is the one place frame bytes decode,
# and compiled scope only ever sees the validated ndarray it returned.
import base64

import jax.numpy as jnp
import numpy as np


# graftlint: payload-boundary — the sanctioned decode of untrusted frames
def validate_payload(frame, policy):
    raw = base64.b64decode(frame["data"], validate=True)
    if len(raw) != policy.nbytes:
        return None, "MALFORMED"
    table = np.frombuffer(raw, dtype="<f4").reshape(policy.rows, policy.cols)
    if not np.isfinite(table).all():
        return None, "QUARANTINED"
    return table, "ACCEPTED"


def merge(state, validated_table):
    # downstream of the gauntlet: a host ndarray, not wire bytes
    return state + jnp.asarray(validated_table)
