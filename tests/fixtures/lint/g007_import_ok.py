# graftlint: module=commefficient_tpu/runner/fake_loop2.py
# G007 package-level conforming twin: the imported helper's wait is a
# DECLARED sanctioned boundary (its module marks the def as a drain point),
# so the cross-module traversal stops there.
from .g007_import_helper_ok import wait_ready


def run_loop(session, cfg):
    for _ in range(cfg.total_rounds):
        wait_ready(session)
        session.dispatch()
