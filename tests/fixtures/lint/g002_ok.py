# graftlint: module=commefficient_tpu/modes/fake_merge.py
# G002 conforming twin: all_gather + ORDERED sum (the sanctioned merge).
from jax import lax


def merge_partial_tables(table_local, axis_names):
    stacked = lax.all_gather(table_local, axis_names, axis=0)
    return stacked.sum(axis=0)
