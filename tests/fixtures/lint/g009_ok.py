# graftlint: module=commefficient_tpu/federated/engine.py
# G009 conforming twin: the compiled body stays pure — jax idioms that
# LOOK like metric mutation (.at[].set scatter) are not obs calls, and the
# host-side telemetry happens in the caller (runner/api), not here.
import jax.numpy as jnp


def make_round_step(cfg):
    def round_step(state, batch, idx):
        update = batch["g"] * 0.1
        # the jax scatter idiom: .set() on an .at[] view is not a gauge
        table = state["table"].at[idx].set(update)
        metrics = {"participants": jnp.sum(batch["mask"])}
        return {**state, "table": table}, metrics

    return round_step
