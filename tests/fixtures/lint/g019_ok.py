"""Conforming twin: the shared counter is mutated under the one declared
lock from both thread roots, and the deliberately lock-free tick counter
carries its `lockfree` declaration.
"""
# graftlint: module=commefficient_tpu/serve/scale/reactor_demo_ok.py

import threading


class Reactor:
    def __init__(self):
        self._lock = threading.Lock()
        self._inflight = 0
        self._ticks = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def submit(self, item):
        with self._lock:
            self._inflight += 1
        # graftlint: lockfree — monotonic GIL-atomic tick counter, read
        # only for coarse progress reporting
        self._ticks += 1
        return item

    def _loop(self):
        while True:
            with self._lock:
                self._inflight -= 1
            self._ticks += 1
