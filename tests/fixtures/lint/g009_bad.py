# graftlint: module=commefficient_tpu/federated/engine.py
# G009 violating twin: obs API calls inside compiled scope — a jitted round
# step that tries to trace/count from inside the traced body.
from ..obs import trace as obtrace
from ..obs.registry import default
from ..obs.trace import span


def make_round_step(cfg):
    reg = default()  # obs registry access in compiled scope

    def round_step(state, batch):
        with span("runner", "inner_step"):  # span inside the traced body
            update = batch["g"] * 0.1
        obtrace.instant("federated", "step_done")  # instant in traced body
        reg.counter("rounds").inc()  # counter mutation in traced body
        registry.gauge("depth").set(1.0)  # registry receiver access
        return state, update

    return round_step
