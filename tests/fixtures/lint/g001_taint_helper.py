"""Helper the taint fixtures import: the float() hides HERE, one call
deep — outside compiled scope, invisible to the syntactic G001 scan.
"""


def coerce_scale(v):
    return float(v)
