# graftlint: module=commefficient_tpu/serve/scale/fake_helper.py
# Helper module for the G017 transitive fixture: the jax import a
# worker-entry module pulls in one hop away.
import jax
import jax.numpy as jnp


def device_merge(stack):
    return jax.jit(jnp.sum)(stack)
