"""The driver's contract: entry() compiles single-chip; dryrun_multichip(8)
jits the full sharded training step on the 8-device CPU mesh."""

import jax

import __graft_entry__ as graft


def test_entry_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)
