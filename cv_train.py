#!/usr/bin/env python
"""CV federated training CLI (SURVEY.md L6: reference `cv_train.py` —
CIFAR-10/100 + FEMNIST experiment driver, same flag surface, dispatching to
the TPU engine instead of worker processes).

Example (paper config #2, SURVEY.md §6):
    python cv_train.py --dataset cifar10 --mode sketch --num_clients 10000 \
        --num_workers 100 --k 50000 --num_rows 5 --num_cols 500000 \
        --num_epochs 24 --lr_scale 0.4 --pivot_epoch 5
Smoke test (BASELINE config #1):
    python cv_train.py --dataset cifar10 --mode uncompressed --num_clients 10 \
        --num_workers 2 --num_rounds 20
"""

from __future__ import annotations

import math
import sys

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

from commefficient_tpu import obs
from commefficient_tpu.data.cifar import load_cifar_fed
from commefficient_tpu.data.femnist import load_femnist_fed
from commefficient_tpu.federated.api import FederatedSession, FedModel, FedOptimizer
from commefficient_tpu.models.femnist_cnn import FEMNISTCNN
from commefficient_tpu.models.losses import make_classification_loss
from commefficient_tpu.models.resnet9 import ResNet9
from commefficient_tpu.parallel import mesh as meshlib
from commefficient_tpu.resilience import FaultPlan, RetryPolicy
from commefficient_tpu.runner import RunnerConfig, run_loop
from commefficient_tpu.serve.service import service_from_args
from commefficient_tpu.utils import checkpoint as ckpt
from commefficient_tpu.utils.config import make_parser, mode_config_from_args, resolve_defaults
from commefficient_tpu.utils.logging import TableLogger
from commefficient_tpu.utils.schedules import triangular


def build(args, fault_plan=None, retry_policy=None):
    # direct callers (tests) pass args only; main() parses once and shares
    # the SAME plan with distributed init and checkpoint IO so per-site
    # injection counters stay coherent across the whole run
    if fault_plan is None:
        fault_plan = FaultPlan.parse(args.fault_plan)
    if retry_policy is None:
        retry_policy = RetryPolicy(max_retries=args.max_retries)
    if args.dataset == "femnist":
        train_set, test_set, num_classes = load_femnist_fed(
            args.data_root, args.num_clients, args.seed
        )
        model = FEMNISTCNN(num_classes=num_classes, dtype=args.dtype)
        sample_shape = (1, 28, 28, 1)
    else:
        train_set, test_set, num_classes = load_cifar_fed(
            args.dataset, args.num_clients, args.iid, args.data_root, args.seed,
            synthetic_separation=args.synthetic_separation,
            synthetic_train=args.synthetic_train,
        )
        model = ResNet9(num_classes=num_classes, dtype=args.dtype)
        sample_shape = (1, 32, 32, 3)
    args.num_clients = train_set.num_clients  # actual shard count

    variables = model.init(jax.random.PRNGKey(args.seed), jnp.zeros(sample_shape), train=False)
    params = variables["params"]
    net_state = {k: v for k, v in variables.items() if k != "params"}
    d = ravel_pytree(params)[0].size
    print(f"model: {type(model).__name__}  d={d:,}  clients={train_set.num_clients}  "
          f"mode={args.mode}", flush=True)

    mode_cfg = mode_config_from_args(args, d)
    if args.mesh:
        mesh = meshlib.make_mesh_from_spec(args.mesh)
    elif jax.device_count() > 1:
        mesh = meshlib.make_mesh(args.num_devices or None)
    else:
        mesh = None
    if mesh is not None:
        from commefficient_tpu.parallel.distributed import mesh_info

        print(f"mesh: {mesh_info(mesh)}", flush=True)
    session = FederatedSession(
        train_loss_fn=make_classification_loss(model, train=True),
        eval_loss_fn=make_classification_loss(model, train=False),
        params=params,
        net_state=net_state,
        mode_cfg=mode_cfg,
        train_set=train_set,
        num_workers=args.num_workers,
        local_batch_size=args.local_batch_size,
        weight_decay=args.weight_decay,
        seed=args.seed,
        mesh=mesh,
        dp_clip=args.dp_clip,
        dp_noise=args.dp_noise,
        client_dropout=args.client_dropout,
        client_update_clip=args.client_update_clip,
        quarantine_window=args.quarantine_window,
        quarantine_scope=args.quarantine_scope,
        # Byzantine-robust table merge (trimmed/median run the per-client-
        # table round; trim=0 trimmed IS sum, bit-identically);
        # --robust_residual on arms the error-feedback-aware residual
        merge_policy=args.merge_policy,
        merge_trim=args.merge_trim,
        robust_residual=getattr(args, "robust_residual", "off") == "on",
        requeue_policy=args.requeue_policy,
        sketch_path=args.sketch_path,
        # --serve_payload sketch inverts the round into the two-program
        # wire shape (client tables + table merge) the service round-trips
        wire_payloads=(getattr(args, "serve", "off") != "off"
                       and args.serve_payload == "sketch"),
        # --serve_async: size the stale-fold merge variant to one cohort's
        # worth of late tables (the buffer trigger bounds how many can
        # straggle per round; the band bounds how long they stay foldable)
        stale_slots=(args.num_workers
                     if getattr(args, "serve_async", False) else 0),
        # --serve_edges >= 2 (linear merge): compile the two-tier edge
        # merge variants (grouped flat twin + partials root). A robust
        # merge_policy runs the tree in FORWARD mode against the plain
        # robust program instead, so the session stays at 0 there.
        serve_edges=(getattr(args, "serve_edges", 0)
                     if args.merge_policy == "sum"
                     or (args.merge_policy == "trimmed"
                         and args.merge_trim == 0) else 0),
        split_compile=args.split_compile,
        client_chunk=args.client_chunk,
        on_nonfinite=args.on_nonfinite,
        fault_plan=fault_plan,
        retry_policy=retry_policy,
        # sketch-health estimators compiled into the round program at the
        # --health_every cadence; --ledger adds per-round state
        # fingerprints (both read-only: armed == unarmed, bit-for-bit).
        # Fingerprints are fused-paths-only — a split ledger run still
        # records cohorts/counters/health, just without them.
        health_every=getattr(args, "health_every", 0),
        ledger_fingerprint=(bool(getattr(args, "ledger", ""))
                            and not args.split_compile),
        # a checkpoint dir arms the watchdog's mid-round emergency save,
        # which needs the live (non-donated) server state readable; the
        # opt-out keeps donation for HBM-tight runs
        donate_state=not (args.checkpoint_dir
                          and not args.no_emergency_checkpoint),
    )
    return session, test_set


def main(argv=None):
    args = resolve_defaults(make_parser("cv").parse_args(argv))
    # arm (or disarm) the obs tracer before anything emits — a traced run
    # is pinned bit-identical to an untraced one (tests/test_obs.py)
    obs.configure_from_args(args)
    fault_plan = FaultPlan.parse(args.fault_plan)
    retry_policy = RetryPolicy(max_retries=args.max_retries)
    from commefficient_tpu.parallel import distributed
    if distributed.initialize_from_args(args, fault_plan=fault_plan,
                                        retry_policy=retry_policy):
        print(f"multihost: {distributed.process_info()}", flush=True)
    session, test_set = build(args, fault_plan, retry_policy)

    rounds_per_epoch = max(1, math.ceil(args.num_clients / session.num_workers))
    total_rounds = args.num_rounds or int(args.num_epochs * rounds_per_epoch)
    if fault_plan is not None:
        # launch-time schedule check: a client_* site at round >=
        # total_rounds could never fire (a vacuous chaos run); likewise a
        # wire_* site on a run with no payload seam to inject at
        fault_plan.validate_rounds(total_rounds)
        fault_plan.validate_wire_context(
            args.serve != "off" and args.serve_payload == "sketch")
        fault_plan.validate_stale_context(
            args.serve != "off" and args.serve_payload == "sketch"
            and getattr(args, "serve_async", False))
        fault_plan.validate_edge_context(
            args.serve != "off" and args.serve_payload == "sketch"
            and getattr(args, "serve_edges", 0) >= 2,
            getattr(args, "serve_edges", 0))
        fault_plan.validate_shard_context(
            args.serve == "socket"
            and getattr(args, "serve_shards", 0) >= 2
            and getattr(args, "serve_shard_mode", "thread") == "process",
            getattr(args, "serve_shards", 0))
    schedule = triangular(args.lr_scale, args.pivot_epoch, args.num_epochs)
    opt = FedOptimizer(schedule, rounds_per_epoch)
    model = FedModel(session)

    if args.resume and args.checkpoint_dir:
        # newest VERIFIED checkpoint; falls back loudly past damaged ones
        path = ckpt.restore_latest(args.checkpoint_dir, session)
        if path:
            opt.round = session.round
            print(f"resumed from {path} at round {session.round}", flush=True)

    if args.profile_dir and not args.profile_rounds:
        # whole-run profiler capture; with --profile_rounds the runner owns
        # a start/stop window around the named rounds instead
        jax.profiler.start_trace(args.profile_dir)

    logger = TableLogger(args.log_jsonl or None)

    def build_row(rnd, m, totals, ev, time_s, nonfinite_total):
        return {
            "round": rnd,
            "epoch": rnd / rounds_per_epoch,
            "lr": m["lr"],
            "train_loss": totals.get("loss_sum", 0.0) / max(totals.get("count", 0.0), 1),
            "train_acc": totals.get("correct", 0.0) / max(totals.get("count", 0.0), 1),
            "test_loss": ev["loss_sum"] / max(ev["count"], 1),
            "test_acc": ev["correct"] / max(ev["count"], 1),
            # measured cumulative wire-cost (checkpointed/restored by
            # the session, so resumed runs stay exact under dropout)
            "comm_mb": session.comm_mb_total,
            "time_s": time_s,
            # always present: TableLogger freezes its columns on the
            # first row, so a count first added mid-run would never
            # reach the stdout table an operator actually watches
            "nonfinite_rounds": nonfinite_total,
        }

    # --health_every / --slo / --ledger: sketch-health monitor, SLO
    # engine, durable round ledger + postmortem bundle — attached AFTER
    # restore so the ledger's resume truncation keys off the restored
    # round (one gap-free, duplicate-free file across preemptions)
    wiring = obs.attach_from_args(args, session)

    # --serve: the streaming aggregation service drives the loop from its
    # push arrival stream (built AFTER restore so a resumed service picks
    # up the persisted pending-submission queue)
    service = service_from_args(args, session)

    # the shared harness owns the loop: block planning, async prefetch /
    # deferred metrics / overlapped checkpoint writes (or the --sync_loop
    # serial path), watchdog escalation, preemption, non-finite halt
    try:
        run_loop(
            session, opt,
            RunnerConfig.from_args(args, total_rounds, args.eval_every or rounds_per_epoch),
            eval_fn=lambda: model.eval(test_set, args.eval_batch_size),
            build_row=build_row,
            logger=logger,
            source=service.source() if service is not None else None,
            slo=wiring.slo_engine,
            postmortem=wiring.postmortem,
        )
    except Exception as e:
        # unhandled-exception postmortem (the watchdog-abort and exit-75
        # bundles are written inside run_loop, where os._exit/sys.exit
        # would skip or outrun this handler)
        if wiring.postmortem is not None:
            wiring.postmortem(f"exception:{type(e).__name__}: {e}")
        raise
    finally:
        wiring.close()
        if service is not None:
            print(f"serve: final metrics {service.metrics_snapshot()}",
                  flush=True)
            service.close()
        # flush the Chrome trace even on the preemption/halt exit paths
        # (sys.exit raises through here): a truncated run with no trace
        # would be useless exactly when the trace matters most
        obs.flush_trace()

    if args.profile_dir and not args.profile_rounds:
        jax.profiler.stop_trace()
    return session


if __name__ == "__main__":
    main(sys.argv[1:])
