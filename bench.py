#!/usr/bin/env python
"""Benchmark: client-updates/sec/chip on the FetchSGD flagship workload
(CIFAR-10 ResNet-9, mode=sketch) — BASELINE.json's north-star metric.

Runs on whatever the default JAX platform is (the driver points this at one
real TPU chip). Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline normalises against REFERENCE_CLIENT_UPDATES_PER_SEC, an estimate
of the reference implementation's single-GPU simulated-client throughput on
the same workload. BASELINE.json's `published` field is empty (no hard
numbers exist in the reference repo — see BASELINE.md); the estimate is
derived from paper-era figures: cifar10-fast ResNet-9 forward+backward at
batch 8 on a V100-class GPU ≈ 4-6k img/s ≈ 600 client-updates/s at 8
imgs/client, minus sketching overhead ≈ 500/s. Re-derive when a populated
reference mount allows measuring directly.
"""

from __future__ import annotations

import json
import time

import os

REFERENCE_CLIENT_UPDATES_PER_SEC = 500.0

# flagship shape: 10k-client federation, 1% participation, paper sketch dims.
# Env overrides exist so the script can be smoke-tested small on CPU
# (BENCH_WORKERS=4 BENCH_COLS=20000 ... python bench.py); the defaults are
# what the driver measures on the real chip.
NUM_WORKERS = int(os.environ.get("BENCH_WORKERS", 64))  # sampled clients/round
LOCAL_BATCH = int(os.environ.get("BENCH_LOCAL_BATCH", 8))  # images per client
SKETCH_ROWS = int(os.environ.get("BENCH_ROWS", 5))
# 2^19 ≈ the paper's 500k, and 128-aligned so the Pallas fast path is eligible
SKETCH_COLS = int(os.environ.get("BENCH_COLS", 524_288))
TOPK = int(os.environ.get("BENCH_TOPK", 50_000))
NUM_BLOCKS = int(os.environ.get("BENCH_BLOCKS", 4))
WARMUP_ROUNDS = int(os.environ.get("BENCH_WARMUP", 3))
TIMED_ROUNDS = int(os.environ.get("BENCH_ROUNDS", 10))


def _pallas_smoke_or_fallback():
    """Try the Pallas sketch kernels on a tiny spec; on any failure fall back
    to the pure-JAX oracle for the whole bench (the kernels are equivalent, so
    this only affects speed, never the measured semantics)."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.sketch import csvec

    spec = csvec.CSVecSpec(d=1000, c=256, r=3, family="rotation")
    if not csvec._use_pallas(spec):
        return
    try:
        from commefficient_tpu.sketch import pallas_kernels as pk

        v = jnp.ones((spec.d,), jnp.float32)
        t = pk.sketch_vec(spec, v)
        jax.block_until_ready(pk.query_all(spec, t))
    except Exception as e:  # compile/runtime failure on this platform
        os.environ["COMMEFFICIENT_NO_PALLAS"] = "1"
        print(f"# pallas kernels unavailable ({type(e).__name__}); using oracle",
              flush=True)


def main():
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    _pallas_smoke_or_fallback()

    from commefficient_tpu.federated import engine
    from commefficient_tpu.models.losses import make_classification_loss
    from commefficient_tpu.models.resnet9 import ResNet9
    from commefficient_tpu.modes.config import ModeConfig

    model = ResNet9(num_classes=10)
    x0 = jnp.zeros((1, 32, 32, 3), dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x0, train=False)
    params = variables["params"]
    net_state = {k: v for k, v in variables.items() if k != "params"}
    d = ravel_pytree(params)[0].size

    mode_cfg = ModeConfig(
        mode="sketch", d=d, k=TOPK, num_rows=SKETCH_ROWS, num_cols=SKETCH_COLS,
        num_blocks=NUM_BLOCKS, momentum_type="virtual", error_type="virtual",
    )
    cfg = engine.EngineConfig(mode=mode_cfg, weight_decay=5e-4)
    state = engine.init_server_state(cfg, params, net_state)
    step = jax.jit(
        engine.make_round_step(make_classification_loss(model, train=True), cfg),
        donate_argnums=(0,),
    )

    key = jax.random.PRNGKey(1)
    batch = {
        "x": jax.random.normal(key, (NUM_WORKERS, LOCAL_BATCH, 32, 32, 3), jnp.float32),
        "y": jax.random.randint(key, (NUM_WORKERS, LOCAL_BATCH), 0, 10, jnp.int32),
        "mask": jnp.ones((NUM_WORKERS, LOCAL_BATCH), jnp.float32),
    }

    for i in range(WARMUP_ROUNDS):
        state, _, _ = step(state, batch, {}, jnp.float32(0.01), jax.random.PRNGKey(i))
    jax.block_until_ready(state["params"])

    t0 = time.perf_counter()
    for i in range(TIMED_ROUNDS):
        state, _, _ = step(state, batch, {}, jnp.float32(0.01), jax.random.PRNGKey(100 + i))
    jax.block_until_ready(state["params"])
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    updates_per_sec_per_chip = (NUM_WORKERS * TIMED_ROUNDS) / dt / n_chips
    print(json.dumps({
        "metric": "client-updates/sec/chip (CIFAR-10 ResNet-9, mode=sketch, "
                  f"r={SKETCH_ROWS} c={SKETCH_COLS} k={TOPK}, {LOCAL_BATCH} img/client)",
        "value": round(updates_per_sec_per_chip, 2),
        "unit": "client-updates/sec/chip",
        "vs_baseline": round(updates_per_sec_per_chip / REFERENCE_CLIENT_UPDATES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
