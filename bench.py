#!/usr/bin/env python
"""Benchmark: client-updates/sec/chip on the FetchSGD flagship workload
(CIFAR-10 ResNet-9, mode=sketch) — BASELINE.json's north-star metric.

Runs on whatever the default JAX platform is (the driver points this at one
real TPU chip). Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "platform": ...}

Robustness contract: a JSON line is ALWAYS emitted. Backend init is probed in
a subprocess with a timeout first, so a broken/hanging TPU plugin (e.g. the
axon tunnel being down) degrades to a CPU run flagged "platform": "cpu"
rather than a crash or a hang. A CPU number can therefore never masquerade as
a TPU number.

vs_baseline normalises against REFERENCE_CLIENT_UPDATES_PER_SEC, an estimate
of the reference implementation's single-GPU simulated-client throughput on
the same workload. BASELINE.json's `published` field is empty (no hard
numbers exist in the reference repo — see BASELINE.md); the estimate is
derived from paper-era figures: cifar10-fast ResNet-9 forward+backward at
batch 8 on a V100-class GPU ≈ 4-6k img/s ≈ 600 client-updates/s at 8
imgs/client, minus sketching overhead ≈ 500/s. Re-derive when a populated
reference mount allows measuring directly. The sketch column count is
recorded in the JSON (c=2^19 vs the paper's 500k — +4.9% sketch size) so
cross-run comparisons stay explicit about the changed dims.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REFERENCE_CLIENT_UPDATES_PER_SEC = 500.0

# flagship shape: 10k-client federation, 1% participation, paper sketch dims.
# Env overrides exist so the script can be smoke-tested small on CPU
# (BENCH_WORKERS=4 BENCH_COLS=20000 ... python bench.py); the defaults are
# what the driver measures on the real chip.
# BENCH_MODEL=resnet9 (default; flagship CIFAR-10 workload) or gpt2
# (PersonaChat-scale: GPT-2-small d~124M, paper config #4 sketch dims —
# num_cols 1M, num_blocks 20; run manually, the driver measures resnet9)
BENCH_MODEL = os.environ.get("BENCH_MODEL", "resnet9")
NUM_WORKERS = int(os.environ.get("BENCH_WORKERS", 64))  # sampled clients/round
LOCAL_BATCH = int(os.environ.get("BENCH_LOCAL_BATCH", 8))  # images per client
SKETCH_ROWS = int(os.environ.get("BENCH_ROWS", 5))
# 2^19 ≈ the paper's 500k, and 128-aligned so the Pallas fast path is eligible
SKETCH_COLS = int(os.environ.get("BENCH_COLS", 524_288))
TOPK = int(os.environ.get("BENCH_TOPK", 50_000))
NUM_BLOCKS = int(os.environ.get("BENCH_BLOCKS", 4))
WARMUP_ROUNDS = int(os.environ.get("BENCH_WARMUP", 3))
TIMED_ROUNDS = int(os.environ.get("BENCH_ROUNDS", 10))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT", 180))


def _probe_backend() -> str | None:
    """Initialise the default JAX backend in a THROWAWAY subprocess and return
    its platform name, or None if init crashes or hangs. Keeps a broken TPU
    plugin from taking this process down (or hanging it) before a JSON line
    can be emitted."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        print("# backend probe timed out; falling back to cpu", flush=True)
        return None
    if out.returncode != 0:
        tail = (out.stderr or "").strip().splitlines()[-1:] or ["?"]
        print(f"# backend probe failed ({tail[0]}); falling back to cpu",
              flush=True)
        return None
    return out.stdout.strip() or None


def _force_cpu() -> None:
    from commefficient_tpu.utils.hermetic import force_hermetic_cpu

    force_hermetic_cpu()


def _pallas_smoke_or_fallback():
    """Try the Pallas sketch kernels on a tiny spec; on any failure fall back
    to the pure-JAX oracle for the whole bench (the kernels are equivalent, so
    this only affects speed, never the measured semantics)."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.sketch import csvec

    try:
        spec = csvec.CSVecSpec(d=1000, c=256, r=3, family="rotation")
        if not csvec._use_pallas(spec):
            return
        from commefficient_tpu.sketch import pallas_kernels as pk

        v = jnp.ones((spec.d,), jnp.float32)
        t = pk.sketch_vec(spec, v)
        jax.block_until_ready(pk.query_all(spec, t))
    except Exception as e:  # compile/runtime failure on this platform
        os.environ["COMMEFFICIENT_NO_PALLAS"] = "1"
        print(f"# pallas kernels unavailable ({type(e).__name__}); using oracle",
              flush=True)


MICROBENCH_D = int(os.environ.get("BENCH_MICRO_D", 6_500_000))


def _kernel_microbench(platform: str) -> dict:
    """Pallas accumulate/query vs the pure-JAX oracle at bench dims.
    Returns timings (ms) or a skip reason; never raises."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.sketch import csvec

    out: dict = {}
    try:
        spec = csvec.CSVecSpec(
            d=MICROBENCH_D, c=SKETCH_COLS, r=SKETCH_ROWS, family="rotation",
            num_blocks=NUM_BLOCKS,
        )
        v = jax.random.normal(jax.random.PRNGKey(0), (spec.d,), jnp.float32)

        def time_fn(f, *args):
            r = jax.block_until_ready(f(*args))  # compile + warm
            t0 = time.perf_counter()
            for _ in range(5):
                r = jax.block_until_ready(f(*args))
            return (time.perf_counter() - t0) / 5 * 1e3, r

        def oracle_query_all(t):
            slabs = jnp.arange(spec.num_slabs, dtype=jnp.int32)
            ests = jax.lax.map(lambda b: csvec._query_slab_rotation(spec, t, b), slabs)
            return ests.reshape(-1)[: spec.d]

        oracle_acc = jax.jit(lambda x: csvec._sketch_vec_rotation(spec, x))
        ms, table = time_fn(oracle_acc, v)
        out["oracle_accumulate_ms"] = round(ms, 3)
        ms, est_o = time_fn(jax.jit(oracle_query_all), table)
        out["oracle_query_ms"] = round(ms, 3)

        if csvec._use_pallas(spec):
            from commefficient_tpu.sketch import pallas_kernels as pk

            pk_acc = jax.jit(lambda x: pk.sketch_vec(spec, x))
            ms, ptable = time_fn(pk_acc, v)
            out["pallas_accumulate_ms"] = round(ms, 3)
            pk_q = jax.jit(lambda t: pk.query_all(spec, t))
            ms, est_p = time_fn(pk_q, ptable)
            out["pallas_query_ms"] = round(ms, 3)
            out["pallas_matches_oracle"] = bool(
                jnp.allclose(table, ptable, atol=1e-3)
                and jnp.allclose(est_o, est_p, atol=1e-3)
            )
        else:
            out["pallas"] = f"ineligible on {platform}"
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _resnet9_workload():
    """Flagship: CIFAR-10 ResNet-9 sketch round (BASELINE config #2 dims)."""
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.models.losses import make_classification_loss
    from commefficient_tpu.models.resnet9 import ResNet9

    model = ResNet9(num_classes=10)
    x0 = jnp.zeros((1, 32, 32, 3), dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x0, train=False)
    params = variables["params"]
    net_state = {k: v for k, v in variables.items() if k != "params"}
    key = jax.random.PRNGKey(1)
    batch = {
        "x": jax.random.normal(key, (NUM_WORKERS, LOCAL_BATCH, 32, 32, 3), jnp.float32),
        "y": jax.random.randint(key, (NUM_WORKERS, LOCAL_BATCH), 0, 10, jnp.int32),
        "mask": jnp.ones((NUM_WORKERS, LOCAL_BATCH), jnp.float32),
    }
    loss_fn = make_classification_loss(model, train=True)
    name = "CIFAR-10 ResNet-9"
    return params, net_state, batch, loss_fn, name, dict(
        k=TOPK, num_rows=SKETCH_ROWS, num_cols=SKETCH_COLS, num_blocks=NUM_BLOCKS
    )


def _gpt2_workload():
    """PersonaChat-scale: GPT-2-small (d ~ 124M), paper config #4 sketch dims
    (c = 1M, 20 blocks). Heavier; workers/seq overridable via env."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models.gpt2 import SMALL, GPT2LMHead
    from commefficient_tpu.models.losses import make_lm_loss

    workers = int(os.environ.get("BENCH_WORKERS", 4))
    seq = int(os.environ.get("BENCH_SEQ", 256))
    global NUM_WORKERS
    NUM_WORKERS = workers
    cfg = dataclasses.replace(SMALL, n_positions=seq, dropout=0.0)
    model = GPT2LMHead(cfg)
    ids0 = jnp.zeros((1, seq), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids0, train=False)["params"]
    key = jax.random.PRNGKey(1)
    ids = jax.random.randint(key, (workers, 2, seq), 0, cfg.vocab_size, jnp.int32)
    batch = {"input_ids": ids, "labels": ids}
    loss_fn = make_lm_loss(model, train=True)
    name = f"GPT-2-small PersonaChat seq={seq}"
    return params, {}, batch, loss_fn, name, dict(
        k=int(os.environ.get("BENCH_TOPK", 50_000)),
        num_rows=SKETCH_ROWS,
        num_cols=int(os.environ.get("BENCH_COLS", 1_048_576)),
        num_blocks=int(os.environ.get("BENCH_BLOCKS", 20)),
    )


def run_bench(platform: str) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    _pallas_smoke_or_fallback()

    from commefficient_tpu.federated import engine
    from commefficient_tpu.modes.config import ModeConfig

    workload = _gpt2_workload if BENCH_MODEL == "gpt2" else _resnet9_workload
    params, net_state, batch, loss_fn, name, sketch_kw = workload()
    d = ravel_pytree(params)[0].size

    mode_cfg = ModeConfig(
        mode="sketch", d=d, momentum_type="virtual", error_type="virtual",
        **sketch_kw,
    )
    cfg = engine.EngineConfig(mode=mode_cfg, weight_decay=5e-4)
    state = engine.init_server_state(cfg, params, net_state)
    step = jax.jit(
        engine.make_round_step(loss_fn, cfg),
        donate_argnums=(0,),
    )

    for i in range(WARMUP_ROUNDS):
        state, _, _ = step(state, batch, {}, jnp.float32(0.01), jax.random.PRNGKey(i))
    jax.block_until_ready(state["params"])

    t0 = time.perf_counter()
    for i in range(TIMED_ROUNDS):
        state, _, _ = step(state, batch, {}, jnp.float32(0.01), jax.random.PRNGKey(100 + i))
    jax.block_until_ready(state["params"])
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    updates_per_sec_per_chip = (NUM_WORKERS * TIMED_ROUNDS) / dt / n_chips
    return {
        "metric": f"client-updates/sec/chip ({name}, mode=sketch, "
                  f"r={mode_cfg.num_rows} c={mode_cfg.num_cols} k={mode_cfg.k})",
        "value": round(updates_per_sec_per_chip, 2),
        "unit": "client-updates/sec/chip",
        "vs_baseline": round(updates_per_sec_per_chip / REFERENCE_CLIENT_UPDATES_PER_SEC, 3),
        "platform": platform,
        "sketch": {"rows": mode_cfg.num_rows, "cols": mode_cfg.num_cols,
                   "k": mode_cfg.k, "blocks": mode_cfg.num_blocks, "d": int(d)},
        "round_ms": round(dt / TIMED_ROUNDS * 1e3, 2),
        "kernel_microbench": _kernel_microbench(platform),
    }


def _shrink_for_cpu():
    """The flagship dims are sized for a TPU chip; on the CPU fallback shrink
    anything the env didn't pin so the script still finishes in minutes."""
    g = globals()
    for name, small in [("NUM_WORKERS", 8), ("TIMED_ROUNDS", 3),
                        ("WARMUP_ROUNDS", 1), ("MICROBENCH_D", 2_000_000)]:
        env_name = {"NUM_WORKERS": "BENCH_WORKERS", "TIMED_ROUNDS": "BENCH_ROUNDS",
                    "WARMUP_ROUNDS": "BENCH_WARMUP", "MICROBENCH_D": "BENCH_MICRO_D"}[name]
        if env_name not in os.environ:
            g[name] = small


def main():
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        platform = "cpu"  # explicitly pinned; no probe needed
    else:
        platform = _probe_backend()
    if platform is None or platform == "cpu":
        _force_cpu()
        platform = "cpu"
        _shrink_for_cpu()
    try:
        result = run_bench(platform)
    except Exception as e:
        # Last-resort: never exit without a JSON line. Retry once on CPU if
        # the failure happened on an accelerator backend.
        print(f"# bench failed on {platform}: {type(e).__name__}: {e}", flush=True)
        if platform != "cpu" and os.environ.get("BENCH_NO_RETRY") != "1":
            try:
                env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_NO_RETRY="1")
                rerun = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                       env=env, timeout=3600)
                if rerun.returncode == 0:
                    return
            except Exception as retry_e:  # timeout etc. — fall through to JSON
                print(f"# cpu retry failed: {type(retry_e).__name__}", flush=True)
        print(json.dumps({
            "metric": "client-updates/sec/chip (CIFAR-10 ResNet-9, mode=sketch)",
            "value": 0.0,
            "unit": "client-updates/sec/chip",
            "vs_baseline": 0.0,
            "platform": platform,
            "error": f"{type(e).__name__}: {e}",
        }))
        return
    print(json.dumps(result))


if __name__ == "__main__":
    main()
