#!/usr/bin/env python
"""Benchmark: client-updates/sec/chip on the FetchSGD flagship workload
(CIFAR-10 ResNet-9, mode=sketch) — BASELINE.json's north-star metric.

Runs on whatever the default JAX platform is (the driver points this at one
real TPU chip). Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "platform": ...}

Timing forensics (round 3): on the tunnelled "axon" platform,
`block_until_ready` returns once the op is *enqueued* remotely, not when it
finishes — round 2's 71,636 updates/s headline was that illusion (it implied
~2 PFLOP/s f32 on one chip). Every timing here therefore uses
`jax.device_get` of a scalar derived from the final state as the only true
sync, times a CHAIN of K data-dependent rounds per sync, and subtracts the
separately measured tunnel round-trip. The JSON records `device_kind`,
analytic + XLA-cost-analysis FLOPs/round, achieved TFLOP/s, MFU against the
chip's bf16 peak, per-chain round-time percentiles, and a workers scale
check (2x clients ≈ 2x round time, else flagged) so the number is auditable.

The JSON also carries a `run_loop` section (a REAL FederatedSession driven
through the shared runner/ harness, --sync_loop-style and async:
`wall_clock_updates_per_sec` + `host_overhead_ms` per arm — the end-to-end
counterpart of the chained compiled-round headline) and a `resilience`
section (nonfinite_rounds, per-site retry counts, checkpoint save-verify
failures; inject faults into the run-loop arms with BENCH_FAULT_PLAN to
benchmark chaos runs).

Robustness contract: a JSON line is ALWAYS emitted. Backend init is probed in
a subprocess with a timeout first, so a broken/hanging TPU plugin (e.g. the
axon tunnel being down) degrades to a CPU run flagged "platform": "cpu"
rather than a crash or a hang. A CPU number can therefore never masquerade as
a TPU number.

vs_baseline normalises against a PER-WORKLOAD estimate of the reference
implementation's single-GPU simulated-client throughput on the same workload
(_REFERENCE_BY_MODEL — a GPT-2 client update costs ~1000x a CIFAR one, so a
single constant would make one of the two numbers meaningless).
BASELINE.json's `published` field is empty (no hard numbers exist in the
reference repo — see BASELINE.md); each estimate's derivation is embedded in
the JSON (`vs_baseline_reference`). Re-derive when a populated reference
mount allows measuring directly. The sketch column count is recorded in the
JSON (c=2^19 vs the paper's 500k — +4.9% sketch size) so cross-run
comparisons stay explicit about the changed dims.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Per-workload: a GPT-2 client update costs ~1000x a CIFAR one, so dividing
# the gpt2 throughput by the ResNet-9 constant made vs_baseline meaningless
# for that workload (r4 first run recorded 0.011 against the wrong yardstick).
# gpt2 estimate: 8 seqs x 256 tok through d=124M fwd+bwd ~ 1.5 TFLOP/client;
# a V100-class GPU at a realistic 30-40 TFLOP/s delivered => ~40-60 ms/client
# => ~15/s serial, and the reference's queue/shm round trip + unsketch at
# c=2^20 eats some of it => ~15/s.
_REFERENCE_BY_MODEL = {
    "resnet9": (500.0,
                "no published reference numbers exist (BASELINE.md); "
                "estimate: cifar10-fast ResNet-9 fwd+bwd ~4-6k img/s on a "
                "V100-class GPU => ~600 client-updates/s at 8 img/client, "
                "minus sketching overhead => 500/s"),
    "gpt2": (15.0,
             "no published reference numbers exist (BASELINE.md); estimate: "
             "~1.5 TFLOP/client (8 seq x 256 tok, d=124M, fwd+bwd) on a "
             "V100-class GPU at 30-40 TFLOP/s delivered => ~40-60 ms/client "
             "=> ~15 client-updates/s incl. queue/shm + unsketch overhead"),
}
# resolved below, right after BENCH_MODEL is validated


def _stage(msg: str) -> None:
    """Progress marker on stderr (stdout carries only the JSON contract line).
    Timestamped + flushed so a wedged tunnel run shows exactly which stage
    stalled (device claim vs compile vs timed chains) in the captured log."""
    print(f"# [{time.strftime('%H:%M:%S')}] bench: {msg}", file=sys.stderr,
          flush=True)

# (d, k) pairs whose approx/oversample effective recall the on-chip probe
# (scripts/topk_recall_probe.py) actually measured; the artifact's
# topk_provenance string is gated on membership so overridden dims never
# claim a measurement that does not exist
_PROBED_TOPK_DIMS = {(6_573_130, 50_000), (123_849_984, 50_000)}

# bf16 peak FLOP/s per chip by device_kind substring (public spec sheets);
# used only to report MFU — unknown kinds record mfu: null
_PEAK_BF16 = [
    ("v5 lite", 197e12),  # TPU v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6 lite", 918e12),  # Trillium
    ("v6e", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]

# flagship shape: 10k-client federation, 1% participation, paper sketch dims.
# Env overrides exist so the script can be smoke-tested small on CPU
# (BENCH_WORKERS=4 BENCH_COLS=20000 ... python bench.py); the defaults are
# what the driver measures on the real chip.
# BENCH_MODEL=resnet9 (default; flagship CIFAR-10 workload) or gpt2
# (PersonaChat-scale: GPT-2-small d~124M, paper config #4 sketch dims —
# num_cols 2^20, num_blocks 20; run manually, the driver measures resnet9)
BENCH_MODEL = os.environ.get("BENCH_MODEL", "resnet9")
if BENCH_MODEL not in ("resnet9", "gpt2"):
    raise SystemExit(f"BENCH_MODEL must be resnet9|gpt2, got {BENCH_MODEL!r}")
REFERENCE_CLIENT_UPDATES_PER_SEC, REFERENCE_DERIVATION = _REFERENCE_BY_MODEL[BENCH_MODEL]
# sampled clients/round. gpt2 defaults to W=64: the sketch-server step is
# W-independent (58 ms at d=124M, BENCH_gpt2_phases_r05.json), so the
# per-chip updates/s headline is server-wall-bound until the cohort
# amortizes it — measured at client_chunk 8: 106.25/s @W=32, 121.03
# @W=64 (MFU 24.4%), 129.85 @W=128 (MFU 26.2%; +7% per further
# doubling at linearly growing bench wall — W=64 is the balance point).
# THE single source of the cohort size: workload builders, phase chains,
# and _make_step's chunk default all read this.
NUM_WORKERS = int(os.environ.get("BENCH_WORKERS", 64))
# per-client unit of work: images (resnet9) or sequences (gpt2) per client
LOCAL_BATCH = int(os.environ.get("BENCH_LOCAL_BATCH",
                                 8 if BENCH_MODEL == "resnet9" else 2))
if BENCH_MODEL == "gpt2":
    # The 15/s estimate above is for the paper-ish 8 seq x 256 tok client.
    # This bench's default gpt2 client is SMALLER (2 seq x BENCH_SEQ tok), so
    # vs_baseline must compare per-client units of the SAME token count:
    # scale the reference linearly in tokens/client (fwd+bwd cost is linear
    # in tokens at fixed d). Round 4's committed 5.27/s was at the 2x256
    # unit, i.e. 0.088 of the token-normalized reference, not the 0.351 a
    # unit-blind division suggests — this scaling makes the JSON carry the
    # honest ratio automatically.
    _GPT2_SEQ = int(os.environ.get("BENCH_SEQ", 256))
    _ref_tokens, _our_tokens = 8 * 256, LOCAL_BATCH * _GPT2_SEQ
    _base_ref = REFERENCE_CLIENT_UPDATES_PER_SEC
    REFERENCE_CLIENT_UPDATES_PER_SEC *= _ref_tokens / _our_tokens
    REFERENCE_DERIVATION += (
        f"; token-normalized to this bench's client unit ({LOCAL_BATCH} seq"
        f" x {_GPT2_SEQ} tok): {_base_ref:g}/s x {_ref_tokens}/{_our_tokens}"
        f" = {REFERENCE_CLIENT_UPDATES_PER_SEC:.3g}/s")
    if os.environ.get("BENCH_GPT2_SIZE") == "tiny":
        # tiny is a smoke/probe knob; its per-client cost has nothing to do
        # with the d=124M reference estimate, so the ratio must not pretend
        REFERENCE_CLIENT_UPDATES_PER_SEC = 0.0
        REFERENCE_DERIVATION = (
            "BENCH_GPT2_SIZE=tiny is a smoke/probe configuration with no "
            "reference counterpart; vs_baseline is pinned 0 and the basis "
            "probe is skipped (the d=124M estimate would be a different "
            "workload)")
SKETCH_ROWS = int(os.environ.get("BENCH_ROWS", 5))
# 2^19 ≈ the paper's 500k, and 1024-aligned so the Pallas fast path is eligible
SKETCH_COLS = int(os.environ.get("BENCH_COLS", 524_288))
TOPK = int(os.environ.get("BENCH_TOPK", 50_000))
NUM_BLOCKS = int(os.environ.get("BENCH_BLOCKS", 4))
WARMUP_ROUNDS = int(os.environ.get("BENCH_WARMUP", 3))
# model compute dtype; bfloat16 (default) is the TPU-native choice — convs/
# matmuls on the MXU at full rate, params/BN/logits f32 (cifar10-fast trains
# half-precision too). BENCH_DTYPE=float32 measures the f32 path.
BENCH_DTYPE = os.environ.get("BENCH_DTYPE", "bfloat16")
if BENCH_DTYPE not in ("float32", "bfloat16"):  # models silently f32 otherwise
    raise SystemExit(f"BENCH_DTYPE must be float32|bfloat16, got {BENCH_DTYPE!r}")
# Engine sketch path: "auto" (default) lets the library route to the Pallas
# kernels when eligible (on CPU they are ineligible, so a tunnel-down
# fallback run still reads engine_sketch_path=oracle); "oracle" pins the
# round step to the pure-JAX sketch. Auto became the default in round 5:
# the wedge-prone compile was the FUSED engine module with Pallas
# custom-calls inlined (ROUND3_NOTES.md), and the split compile below —
# also now the default — keeps the Mosaic-bearing module small and
# structurally identical to the standalone kernel compile proven on this
# chip (round-4 step 5). The driver's unattended capture therefore rides
# the Pallas path whenever the chip answers, which is the artifact
# VERDICT r4 #1 requires.
BENCH_ENGINE_SKETCH = os.environ.get("BENCH_ENGINE_SKETCH", "auto")
if BENCH_ENGINE_SKETCH not in ("oracle", "auto"):
    raise SystemExit(f"BENCH_ENGINE_SKETCH must be oracle|auto, got {BENCH_ENGINE_SKETCH!r}")
# The knob is authoritative over any inherited COMMEFFICIENT_NO_PALLAS value
# (an empty-string "unset" must not silently re-enable the wedge-prone
# compile in oracle mode; a stale =1 export must not silently undermine auto)
if BENCH_ENGINE_SKETCH == "oracle":
    os.environ["COMMEFFICIENT_NO_PALLAS"] = "1"
else:
    os.environ.pop("COMMEFFICIENT_NO_PALLAS", None)
# Engine compile shape: "split" (default; see above) compiles the sketch
# server step (the only Mosaic-bearing part when BENCH_ENGINE_SKETCH=auto)
# as its own small module — the wedge-avoidance path
# (engine.make_split_round_step); one extra dispatch per round. "fused" is
# one XLA program per round — the historical wedge trigger when Pallas
# custom-calls are inlined (window phase F probes it with an XLA dump).
BENCH_ENGINE_COMPILE = os.environ.get("BENCH_ENGINE_COMPILE", "split")
if BENCH_ENGINE_COMPILE not in ("fused", "split"):
    raise SystemExit(
        f"BENCH_ENGINE_COMPILE must be fused|split, got {BENCH_ENGINE_COMPILE!r}")
# timed work = BENCH_CHAINS chains of BENCH_CHAIN_LEN dependent rounds, one
# device_get sync per chain (>= 30 rounds total for stable percentiles)
CHAIN_LEN = int(os.environ.get("BENCH_CHAIN_LEN", 10))
NUM_CHAINS = int(os.environ.get("BENCH_CHAINS", 4))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT", 180))
SCALE_CHECK = os.environ.get("BENCH_SCALE_CHECK", "1") == "1"


def _probe_backend() -> str | None:
    """Initialise the default JAX backend in a THROWAWAY subprocess and return
    its platform name, or None if init crashes or hangs. Keeps a broken TPU
    plugin from taking this process down (or hanging it) before a JSON line
    can be emitted."""
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        print("# backend probe timed out; falling back to cpu", flush=True)
        return None
    if out.returncode != 0:
        tail = (out.stderr or "").strip().splitlines()[-1:] or ["?"]
        print(f"# backend probe failed ({tail[0]}); falling back to cpu",
              flush=True)
        return None
    return out.stdout.strip() or None


def _force_cpu() -> None:
    from commefficient_tpu.utils.hermetic import force_hermetic_cpu

    force_hermetic_cpu()


def _tunnel_round_trip_ms() -> float:
    """Median host<->device sync cost (device transfer + tunnel latency on
    axon; ~us locally). Subtracted from every chain timing."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.float32(0.0)
    _ = jax.device_get(f(x))
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        _ = jax.device_get(f(x))
        samples.append((time.perf_counter() - t0) * 1e3)
    return sorted(samples)[len(samples) // 2]


def _pallas_status() -> dict:
    """Library-level probe outcome (full traceback preserved on failure)."""
    from commefficient_tpu.sketch import pallas_kernels

    return pallas_kernels.probe_status()


def _time_adaptive(fn_of_n, args: tuple, n0: int, rt_ms: float,
                   cap: int = 4096):
    """RTT-adaptive chain timing. `fn_of_n(n)` returns a jittable function
    computing an n-iteration data-dependent chain over `args`; the helper
    owns the compile/warm/device_get-sync timing discipline for every timer
    in this file. A chain shorter than the tunnel round-trip (~70 ms on a
    bad day) measures as ~0 after the rt_ms subtraction, so: measure once at
    n0, and if the chain doesn't dwarf the RTT, use that first measurement
    to jump straight to the needed length (one extra compile at most,
    capped). Returns (per_iteration_ms, n_used, rtt_dominated) —
    `rtt_dominated` means the chain never met the 4x-RTT target (cap bit
    first) and the value is jitter-dominated/untrustworthy."""
    import math

    import jax

    def run(n):
        g = jax.jit(fn_of_n(n))
        _ = jax.device_get(g(*args))  # compile + warm
        t0 = time.perf_counter()
        _ = jax.device_get(g(*args))
        return (time.perf_counter() - t0) * 1e3

    n = n0
    total = run(n)
    target = 4 * rt_ms
    if total < target and n < cap:
        # Extrapolate from the estimated COMPUTE time (total minus RTT), not
        # the RTT-inflated total — in the RTT-dominated case the inflated
        # total would rescale to a chain still far too short. 25% headroom;
        # at least double so progress is real even on a noisy first sample.
        compute = max(total - rt_ms, 1e-3)
        n = min(cap, max(2 * n, math.ceil(n * 1.25 * target / compute)))
        total = run(n)
    per = max(total - rt_ms, 0.0) / n
    # trustworthy only when the chain met the 4x-RTT design target — a
    # nonzero but RTT-jitter-dominated value must not look like a normal
    # measurement (can happen when the cap bites on an ultra-fast kernel)
    return per, n, (total < target)


MICROBENCH_D = int(os.environ.get("BENCH_MICRO_D", 6_500_000))
MICRO_CHAIN = int(os.environ.get("BENCH_MICRO_CHAIN", 20))
# Per-phase timing (VERDICT r3 #4): time the client fwd/bwd+reduce program
# and the sketch-server program (accumulate + FetchSGD algebra + the d-length
# unsketch_topk) as separate data-dependent chains. Default on for gpt2 —
# at d=124M, c=2^20 the unsketch median query is the suspected wall; measure
# it, don't guess. (Two extra Mosaic-free compiles; BENCH_PHASE_TIMING=0/1
# overrides.)
PHASE_TIMING = os.environ.get("BENCH_PHASE_TIMING", "1") == "1"
# (default on for resnet9 too since r4's first hardware run: its scale check
# came back flat at 1.27, and client_ms vs server_ms is exactly the evidence
# that says whether that's the W-independent oracle sketch server step —
# expected — or an async-timing illusion)
PHASE_CHAIN = int(os.environ.get("BENCH_PHASE_CHAIN", 6))
# Finer server attribution (accumulate | estimates | top-k exact vs approx),
# each at the engine's real sketch dims — at GPT-2 scale the exact
# `lax.top_k` over d=124M is the suspected wall inside server_ms, and the
# approx number quantifies the ModeConfig.topk_impl="approx" remedy in the
# same JSON. BENCH_SERVER_SPLIT=0/1 overrides.
SERVER_SPLIT = os.environ.get("BENCH_SERVER_SPLIT", "1") == "1"
# vs_baseline derivation from a measurement (VERDICT r3 #7): time ONE
# client's fwd+bwd in f32 on this chip (ResNet-9 at batch 8, or GPT-2 at
# this bench's seqs-per-client), so the JSON carries the arithmetic behind
# the baseline multiple instead of only a remembered constant.
BASELINE_BASIS = os.environ.get("BENCH_BASELINE_BASIS", "1") == "1"
# End-to-end run-loop harness measurement (runner/): drive a REAL
# FederatedSession (host sampling + native batch assembly + dispatch +
# metrics + bookkeeping) through the shared run loop, --sync_loop-style and
# async, on the flagship workload. Reports wall_clock_updates_per_sec and
# host_overhead_ms (wall-clock round minus the compiled round measured by
# the timed chains) for BOTH loops, so the overlap win is a measured
# headline, not a claim. resnet9 only (the flagship the driver measures).
RUN_LOOP = os.environ.get("BENCH_RUN_LOOP", "1") == "1"
RUN_LOOP_ROUNDS = int(os.environ.get("BENCH_RUN_LOOP_ROUNDS", 30))
# Streaming-aggregation service section (serve/): (a) sustained ingest
# throughput (accepted client-updates/s) through the admission-control path
# under the diurnal trace, (b) host-memory flatness of the O(1) fold_in
# client state at a 10M-ID population vs 10k (the no-per-client-table
# acceptance check), (c) submission-to-merge latency p50/p99 through a REAL
# served session (invite -> push -> W-of-N close -> dispatch -> commit),
# (e) the --serve_fastpath A/B over the loopback socket: submission-to-merge
# p50/p99 and bytes_touched_per_table, slow path vs pinned ring + batched
# gauntlet + ingest/H2D overlap (same trace, same seed).
# resnet9 only, like run_loop; {"skipped": ...} when unavailable.
# ravel-vs-layerwise sketch accumulation A/B on the run_loop bench (resnet9
# only): updates/s + per-round ms through the REAL async runner for both
# --sketch_path arms, plus the HBM headline — peak live-buffer bytes of the
# compiled fused round program per arm (XLA memory_analysis: temp + output,
# arguments excluded since both arms bind identical params/batch buffers).
# BENCH_SKETCH_PATH=0 disables (the tier-1 smoke does).
SKETCH_PATH_BENCH = os.environ.get("BENCH_SKETCH_PATH", "1") == "1"
SERVE_BENCH = os.environ.get("BENCH_SERVE", "1") == "1"
# obs.health arm: estimator overhead (--health_every 1 vs off on the warm
# runner) + recall-proxy vs dense-truth agreement. BENCH_HEALTH=0
# disables; BENCH_HEALTH_ROUNDS sizes it; BENCH_HEALTH_COLS pins the
# dense-comparable geometry (default keeps k/c <= 1/16).
HEALTH_BENCH = os.environ.get("BENCH_HEALTH", "1") == "1"
HEALTH_ROUNDS = int(os.environ.get("BENCH_HEALTH_ROUNDS", 12))
SERVE_ROUNDS = int(os.environ.get("BENCH_SERVE_ROUNDS", 12))
SERVE_POPULATION = int(os.environ.get("BENCH_SERVE_POPULATION", 10_000_000))
# Byzantine-robustness section: final accuracy under each adversarial
# client kind x {sum, trimmed, median} merge on the flagship task, plus the
# merge-policy overhead in updates/s (the robust policies forfeit the
# compress-once shortcut — this measures what the defense costs). 12 short
# real runs; BENCH_BYZANTINE=0 disables, BENCH_BYZANTINE_ROUNDS sizes them.
BYZANTINE_BENCH = os.environ.get("BENCH_BYZANTINE", "1") == "1"
BYZANTINE_ROUNDS = int(os.environ.get("BENCH_BYZANTINE_ROUNDS", 20))
# C1M scale-out section (serve/scale/): (a) sustained submissions/s vs
# concurrent-connection count for the threaded vs event-loop socket
# transports (the reactor must hold >= 10x the threaded transport's
# concurrent connections on this box — the transports' architectural
# ceilings ARE the result), (b) edge-tree vs flat merge wall-clock at
# W=256 through real served sessions, (c) process-shard strong scaling:
# submissions/s vs 1/2/4/8 SO_REUSEPORT shard worker processes under the
# multi-process closed-loop loadgen (>= 2x at 4 processes on a multi-core
# box; skipped-with-reason on 1 core), and (d) the loadgen ramp from 2048
# toward BENCH_LOADGEN_CONNS (default 100k) connections, recording the
# fd/rlimit ceiling the box actually hits. Off by default (opens
# thousands of loopback sockets and raises RLIMIT_NOFILE to its hard
# cap); BENCH_SCALE=1 enables, BENCH_SCALE_CONNS caps the transport ramp,
# BENCH_SCALE_ROUNDS sizes the edge arm, BENCH_LOADGEN_CONNS the ramp.
SCALE_BENCH = os.environ.get("BENCH_SCALE", "0") == "1"
SCALE_CONNS = int(os.environ.get("BENCH_SCALE_CONNS", 2048))
SCALE_ROUNDS = int(os.environ.get("BENCH_SCALE_ROUNDS", 3))
LOADGEN_CONNS = int(os.environ.get("BENCH_LOADGEN_CONNS", 100_000))
# Mesh scaling section: time the SPMD sharded round (engine.
# make_sharded_round_step — per-device partial sketch + one table merge)
# at the same global cohort across 1, 2, 4, ... visible devices, and record
# the comm-efficiency headline: sketch-table merge bytes vs the dense [d]
# all-reduce a gradient-synchronous round would ship. Degrades to
# {"skipped": ...} on a single device — the flagship single-chip headline
# is unaffected. BENCH_MESH=0 disables; =1 also opts in when the Pallas
# engine path is routed (a Mosaic-bearing shard_map module is an unproven
# compile shape on the wedge-prone chip, same caveat as phase_timing).
MESH_BENCH = os.environ.get("BENCH_MESH", "1") == "1"
MESH_CHAINS = int(os.environ.get("BENCH_MESH_CHAINS", 2))
# Optional fault plan injected into the run-loop section's session, making
# chaos runs benchmarkable: the JSON's `resilience` block then carries the
# nonfinite_rounds and per-site retry counts the plan provoked. preempt
# specs are stripped (a SIGTERM would turn the bench itself into a
# resumable exit instead of a JSON line).
BENCH_FAULT_PLAN = os.environ.get("BENCH_FAULT_PLAN", "")


def _kernel_microbench(platform: str, rt_ms: float) -> dict:
    """Pallas accumulate+query vs the pure-JAX oracle at bench dims, timed as
    a data-dependent in-jit chain (sketch -> query -> next input) with ONE
    device_get sync — immune to async dispatch. Returns per-iteration ms for
    the PAIR, or a skip reason; never raises."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.sketch import csvec

    out: dict = {}
    try:
        spec = csvec.CSVecSpec(
            d=MICROBENCH_D, c=SKETCH_COLS, r=SKETCH_ROWS, family="rotation",
            num_blocks=NUM_BLOCKS,
        )
        v = jax.random.normal(jax.random.PRNGKey(0), (spec.d,), jnp.float32)

        def chain(x, acc_fn, q_fn, n):
            def body(carry, _):
                est = q_fn(acc_fn(carry))
                return est, None  # next input IS the estimates: no dead code

            y, _ = jax.lax.scan(body, x, None, length=n)
            return y[0]

        def time_pair(label, acc_fn, q_fn):
            per, n, rtt_dominated = _time_adaptive(
                lambda n: (lambda x: chain(x, acc_fn, q_fn, n)), (v,),
                MICRO_CHAIN, rt_ms)
            out.setdefault("chain_lens", {})[label] = n
            if rtt_dominated:
                # which pass is untrustworthy, not just that one is
                out.setdefault("rtt_dominated", []).append(label)
            return per

        def oracle_q(tab):
            slabs = jnp.arange(spec.num_slabs, dtype=jnp.int32)
            ests = jax.lax.map(
                lambda b: csvec._query_slab_rotation(spec, tab, b), slabs
            )
            return ests.reshape(-1)[: spec.d]

        out["oracle_pair_ms"] = round(
            time_pair("oracle",
                      lambda x: csvec._sketch_vec_rotation(spec, x), oracle_q), 3
        )

        # Measure the kernels directly whenever they compile on this backend.
        # Deliberately NOT csvec._use_pallas: COMMEFFICIENT_NO_PALLAS steers
        # only the library/engine routing (so a wedge-prone engine compile can
        # be avoided) while the microbench still characterises the kernels.
        from commefficient_tpu.sketch import pallas_kernels as pk

        if pk.eligible(spec):
            out["pallas_pair_ms"] = round(
                time_pair(
                    "pallas",
                    lambda x: pk.sketch_vec(spec, x),
                    lambda t: pk.query_all(spec, t),
                ),
                3,
            )
            table = jax.jit(lambda x: pk.sketch_vec(spec, x))(v)
            otable = jax.jit(lambda x: csvec._sketch_vec_rotation(spec, x))(v)
            est_p = jax.jit(lambda t: pk.query_all(spec, t))(otable)
            est_o = jax.jit(oracle_q)(otable)
            out["pallas_matches_oracle"] = bool(
                jnp.allclose(table, otable, atol=1e-3)
                and jnp.allclose(est_p, est_o, atol=1e-3)
            )
            if (out["oracle_pair_ms"] > 0 and out["pallas_pair_ms"] > 0
                    and not out.get("rtt_dominated")):
                # all three guards matter: a clamped-to-0 OR jitter-dominated
                # pass would publish a bogus speedup (the r2/r3 failure mode
                # this file exists to prevent)
                out["pallas_speedup_vs_oracle"] = round(
                    out["oracle_pair_ms"] / out["pallas_pair_ms"], 2
                )
        else:
            out["pallas"] = f"ineligible on {platform}"
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _resnet9_workload():
    """Flagship: CIFAR-10 ResNet-9 sketch round (BASELINE config #2 dims)."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models.losses import make_classification_loss
    from commefficient_tpu.models.resnet9 import ResNet9

    model = ResNet9(num_classes=10, dtype=BENCH_DTYPE)
    x0 = jnp.zeros((1, 32, 32, 3), dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x0, train=False)
    params = variables["params"]
    net_state = {k: v for k, v in variables.items() if k != "params"}
    # one key per draw (graftlint G006): x and y from the same key would be
    # correlated streams — harmless for a timing batch, but the parity rules
    # hold benchmark code to the same discipline as the engine
    kx, ky = jax.random.split(jax.random.PRNGKey(1))
    workers = NUM_WORKERS
    batch = {
        "x": jax.random.normal(kx, (workers, LOCAL_BATCH, 32, 32, 3), jnp.float32),
        "y": jax.random.randint(ky, (workers, LOCAL_BATCH), 0, 10, jnp.int32),
        "mask": jnp.ones((workers, LOCAL_BATCH), jnp.float32),
    }
    loss_fn = make_classification_loss(model, train=True)
    name = "CIFAR-10 ResNet-9"
    sketch_kw = dict(
        k=TOPK, num_rows=SKETCH_ROWS, num_cols=SKETCH_COLS, num_blocks=NUM_BLOCKS
    )
    return params, net_state, batch, loss_fn, name, sketch_kw, workers


def _gpt2_model(dtype):
    """GPT-2 config+model shared by _gpt2_workload and _baseline_basis, so
    the basis probe measures definitionally the same client as the headline
    metric. BENCH_GPT2_SIZE=tiny exists for cheap smoke/probe runs (CPU
    fallback, fused-compile forensics); the headline metric is always
    "small" (and tiny pins the reference to 0 — see the knob block up top)."""
    import dataclasses

    from commefficient_tpu.models.gpt2 import SMALL, TINY, GPT2LMHead

    seq = int(os.environ.get("BENCH_SEQ", 256))
    base = TINY if os.environ.get("BENCH_GPT2_SIZE") == "tiny" else SMALL
    cfg = dataclasses.replace(base, n_positions=seq, dropout=0.0, dtype=dtype)
    size = "tiny" if base is TINY else "small"
    return cfg, GPT2LMHead(cfg), seq, size


def _gpt2_workload():
    """PersonaChat-scale: GPT-2-small (d ~ 124M), paper config #4 sketch dims
    (c = 2^20, 20 blocks). Heavier; workers/seq overridable via env."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.models.losses import make_lm_loss

    # cohort size: NUM_WORKERS (single source; see its comment).
    # client_chunk (default gcd(8, NUM_WORKERS), _make_step) bounds HBM
    # at <= 8 concurrent [d] grads (~4 GB) regardless of W.
    workers = NUM_WORKERS
    cfg, model, seq, size = _gpt2_model(BENCH_DTYPE)
    ids0 = jnp.zeros((1, seq), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids0, train=False)["params"]
    key = jax.random.PRNGKey(1)
    ids = jax.random.randint(
        key, (workers, LOCAL_BATCH, seq), 0, cfg.vocab_size, jnp.int32)
    batch = {"input_ids": ids, "labels": ids}
    loss_fn = make_lm_loss(model, train=True)
    name = f"GPT-2-{size} PersonaChat seq={seq} b={LOCAL_BATCH}"
    sketch_kw = dict(
        k=int(os.environ.get("BENCH_TOPK", 50_000)),
        num_rows=SKETCH_ROWS,
        num_cols=int(os.environ.get("BENCH_COLS", 1_048_576)),
        num_blocks=int(os.environ.get("BENCH_BLOCKS", 20)),
    )
    return params, {}, batch, loss_fn, name, sketch_kw, workers


def _make_step(loss_fn, sketch_kw, d):
    import jax

    from commefficient_tpu.federated import engine
    from commefficient_tpu.modes.config import ModeConfig

    # Default selection: approx@0.99 — the on-chip probe
    # (results/topk_recall_probe_r05.md) measured its effective recall at
    # 1.0000 at flagship dims (the selected SET equals exact lax.top_k's;
    # only boundary tie-breaking differs) and 0.9970 at GPT-2 dims, the
    # 2x2-seed paper-scale study put any accuracy difference within seed
    # variance, and it is +6% flagship round throughput / ~3x GPT-2 round
    # throughput vs exact (the 442-vs-4.4 ms figure is the top-k OP cost;
    # the round also carries client compute). The training CLIs keep
    # exact as THEIR default; BENCH_TOPK_IMPL=exact reproduces the
    # accuracy-faithful bench config.
    mode_cfg = ModeConfig(
        mode="sketch", d=d, momentum_type="virtual", error_type="virtual",
        topk_impl=os.environ.get("BENCH_TOPK_IMPL", "approx"),
        topk_recall=float(os.environ.get("BENCH_TOPK_RECALL", 0.99)),
        **sketch_kw,
    )
    # BENCH_CLIENT_CHUNK > 0 scans grads in client chunks (HBM ceiling for
    # big-cohort GPT-2 rounds; engine._weighted_client_reduce). gpt2
    # defaults to gcd(8, W): 8 concurrent [d] grads (~4 GB) is the
    # measured sweet spot — chunk 4 underfeeds the MXU (86/s @W=32),
    # chunk 16's ~8 GB working set regresses to 88/s vs chunk 8's 106/s.
    # The chunk must divide W (engine raises loudly otherwise), so a
    # W=2 smoke degrades to chunk=2 instead of crashing.
    if BENCH_MODEL == "gpt2":
        import math
        default_chunk = math.gcd(8, NUM_WORKERS)
    else:
        default_chunk = 0
    cfg = engine.EngineConfig(
        mode=mode_cfg, weight_decay=5e-4,
        client_chunk=int(os.environ.get("BENCH_CLIENT_CHUNK", default_chunk)),
        # match the CLI default ("skip"): the headline number must measure
        # the guarded round program production actually runs; pin "off" to
        # A/B the guard's cost
        on_nonfinite=os.environ.get("BENCH_ON_NONFINITE", "skip"),
    )
    if BENCH_ENGINE_COMPILE == "split":
        client_p, server_p = engine.make_split_round_step(loss_fn, cfg)
        cstep = jax.jit(client_p)
        sstep = jax.jit(server_p, donate_argnums=(0,))
        step = engine.compose_split(cstep, sstep)
        step._parts = (cstep, sstep)  # _flops_per_round lowers each half
        return engine, mode_cfg, cfg, step
    # donate the server state, as a real training loop would (every call site
    # rebinds: state, _, _ = step(state, ...)); keeps GPT-2-scale state 1x HBM
    step = jax.jit(engine.make_round_step(loss_fn, cfg), donate_argnums=(0,))
    return engine, mode_cfg, cfg, step


def _timed_chains(step, state, batch, num_chains, chain_len, rt_ms):
    """Run `num_chains` chains of `chain_len` data-dependent rounds; one
    device_get sync per chain. Returns (per-round ms estimates, final state).
    The K dispatches of a chain queue on the device back-to-back (the state
    carry makes each round depend on the previous), so chain time ~= K x
    round time + one sync, and dispatch overlaps compute."""
    import jax
    import jax.numpy as jnp

    per_round_ms = []
    for chain in range(num_chains):
        t0 = time.perf_counter()
        for i in range(chain_len):
            state, _, _ = step(
                state, batch, {}, jnp.float32(0.01),
                jax.random.PRNGKey(1000 + chain * chain_len + i),
            )
        # the ONLY trustworthy sync: pull a scalar that depends on the params
        _ = jax.device_get(state["round"] + jnp.int32(0))
        total_ms = (time.perf_counter() - t0) * 1e3
        per_round_ms.append(max(total_ms - rt_ms, 0.0) / chain_len)
    return per_round_ms, state


def _flops_per_round(step, state, batch, chunk_trips=1):
    """XLA's own cost analysis of the compiled round step (flops for the
    whole round: W clients fwd+bwd + sketch accumulate/query + server step).
    For the split engine, the round is two programs — sum both.

    XLA's HLO cost analysis counts a while-loop (lax.scan) body ONCE, so
    when the client step scans over client chunks (BENCH_CLIENT_CHUNK > 0,
    W > chunk) the client flops come out divided by the trip count —
    BENCH_flagship_w256_r05.json recorded the same flops as W=64 and an MFU
    understated 4x. `chunk_trips` = W // chunk re-scales the client program
    (its flops are ~entirely inside the scan body; the residue outside is
    reduce/compress epsilon). Returns (flops, note_or_None)."""
    import jax
    import jax.numpy as jnp

    def cost_of(lowered):
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost.get("flops", 0.0))

    def note_for(scope):
        if chunk_trips <= 1:
            return None
        return (
            f"{scope} flops scaled x{chunk_trips}: XLA cost analysis "
            "counts the client_chunk lax.scan body once"
        )

    try:
        lr, rng = jnp.float32(0.01), jax.random.PRNGKey(0)
        if hasattr(step, "_parts"):
            cstep, sstep = step._parts
            f1 = cost_of(cstep.lower(state, batch, lr, rng)) * chunk_trips
            w, nns, met, nrng = jax.eval_shape(cstep, state, batch, lr, rng)
            f2 = cost_of(sstep.lower(state, w, nns, met["participants"], lr, nrng))
            total = f1 + f2
            return (total, note_for("client-step")) if total else (None, None)
        lowered = step.lower(state, batch, {}, lr, rng)
        # fused: one program; the scan body holds the client convs, which
        # dominate total flops, so whole-program scaling is a close upper
        # bound (server sketch ops carry few flops — and the note says so)
        total = cost_of(lowered) * chunk_trips
        return (total, note_for(
            "whole-program (server ops included; slight overcount)"
        )) if total else (None, None)
    except Exception:
        return None, None


def _analytic_resnet9_flops(workers: int, local_batch: int) -> float:
    """Analytic check on the XLA number: cifar10-fast ResNet-9 is ~1.31
    GFLOP/image forward (conv+fc MACs x2 at 32x32), fwd+bwd ~= 3x forward."""
    fwd_per_image = 1.31e9
    return workers * local_batch * fwd_per_image * 3.0


def _server_split(mode_cfg, rt_ms) -> dict:
    """Per-op attribution of the sketch-server wall at the workload's REAL
    dims: accumulate (sketch_vec over d), estimates (the d-length median
    query), and the final top-k over d — timed BOTH exact and approx, so the
    JSON itself says whether `lax.top_k` over d is the wall and what
    `approx_max_k` (ModeConfig.topk_impl="approx") would buy. Each op runs
    as its own data-dependent in-jit chain with one device_get sync (the
    same discipline as every timer here); never raises."""
    import jax
    import jax.numpy as jnp

    from commefficient_tpu.sketch import csvec

    spec, k = mode_cfg.sketch_spec, mode_cfg.k
    out: dict = {"d": spec.d, "k": k, "topk_impl_engine": mode_cfg.topk_impl,
                 "topk_recall": mode_cfg.topk_recall}
    try:
        v0 = jax.random.normal(jax.random.PRNGKey(7), (spec.d,), jnp.float32)
        t0 = csvec.sketch_vec(spec, v0)
        e0 = csvec.query_all(spec, t0)

        def acc_chain(v, n):
            def body(x, _):
                table = csvec.sketch_vec(spec, x)
                # scalar feedback keeps rounds dependent without extra d-work
                return x * (1.0 + 1e-12 * table[0, 0]), ()
            x, _ = jax.lax.scan(body, v, None, length=n)
            return x[0]

        def est_chain(table, n):
            def body(t, _):
                est = csvec.query_all(spec, t)
                return t + 1e-12 * est[0], ()
            t, _ = jax.lax.scan(body, table, None, length=n)
            return t[0, 0]

        def topk_chain(impl):
            def chain(est, n):
                def body(x, _):
                    idx = csvec.topk_abs(x, k, impl=impl, recall=mode_cfg.topk_recall)
                    return x + 1e-12 * x[idx[0]], ()
                x, _ = jax.lax.scan(body, est, None, length=n)
                return x[0]
            return chain

        # -------- the former "~22 ms of unattributed algebra" (r5 GPT-2
        # phase split): the sketch-space FetchSGD algebra, the delta apply
        # (scatter vs densify+subtract — engine rides the scatter since the
        # server_step_sparse change), and the params ravel/unravel pair.
        k_idx = (jnp.arange(k, dtype=jnp.int32) * (spec.d // k)) % spec.d
        k_vals = jnp.linspace(1.0, 2.0, k, dtype=jnp.float32)

        def algebra_chain(table, n):
            def body(carry, _):
                V, E = carry
                V = 0.9 * V + table
                E = E + 0.01 * V
                sv = csvec.query(spec, V, k_idx)
                E = E - csvec.sketch_sparse(spec, k_idx, k_vals)
                V = V - csvec.sketch_sparse(spec, k_idx, sv)
                return (V, E), ()
            (V, _), _ = jax.lax.scan(body, (table, table), None, length=n)
            return V[0, 0]

        def apply_sparse_chain(p, n):
            def body(x, _):
                x = x.at[k_idx].add(-(k_vals * (1.0 + 1e-12 * x[0])))
                return x, ()
            x, _ = jax.lax.scan(body, p, None, length=n)
            return x[0]

        def apply_dense_chain(p, n):
            def body(x, _):
                delta = csvec.to_dense(
                    spec.d, k_idx, k_vals * (1.0 + 1e-12 * x[0]))
                return x - delta, ()
            x, _ = jax.lax.scan(body, p, None, length=n)
            return x[0]

        # ravel/unravel at the workload's d: a synthetic ~48-leaf pytree
        # (GPT-2-small has ~148 param leaves; concat/split traffic is what
        # matters, leaf count is second order)
        from jax.flatten_util import ravel_pytree as _ravel
        sizes = [spec.d // 48] * 47
        sizes.append(spec.d - sum(sizes))
        tree0 = {f"w{i}": jnp.ones((s,), jnp.float32)
                 for i, s in enumerate(sizes)}
        _, unravel = _ravel(tree0)

        def ravel_chain(tree, n):
            def body(t, _):
                f, _ = _ravel(t)
                return unravel(f * (1.0 + 1e-12 * f[0])), ()
            t, _ = jax.lax.scan(body, tree, None, length=n)
            return _ravel(t)[0][0]

        for label, fn, arg in (
            ("accumulate_ms", acc_chain, v0),
            ("estimates_ms", est_chain, t0),
            ("topk_exact_ms", topk_chain("exact"), e0),
            ("topk_approx_ms", topk_chain("approx"), e0),
            ("topk_oversample_ms", topk_chain("oversample"), e0),
            ("algebra_sketch_ms", algebra_chain, t0),
            ("delta_apply_sparse_ms", apply_sparse_chain, v0),
            ("delta_apply_dense_ms", apply_dense_chain, v0),
            ("ravel_unravel_ms", ravel_chain, tree0),
        ):
            per, n, rtt_dominated = _time_adaptive(
                lambda n, f=fn: (lambda a_: f(a_, n)), (arg,),
                PHASE_CHAIN, rt_ms)
            out[label] = round(per, 2)
            if rtt_dominated:
                out.setdefault("rtt_dominated", []).append(label)
        out["note"] = ("ops timed in isolation at the engine's sketch spec; "
                      "accumulate+estimates+topk+algebra_sketch+"
                      "delta_apply_sparse+ravel_unravel ~= the whole sketch "
                      "server step (the engine applies deltas via the sparse "
                      "scatter; delta_apply_dense_ms shows what the densify+"
                      "subtract form would cost)")
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _phase_timing(loss_fn, cfg, state, batch, rt_ms) -> dict:
    """Client-phase vs server-phase wall-clock via the split-engine programs
    (engine.make_split_round_step): the client program is the vmapped
    fwd/bwd + survivor reduce; the server program is compress(weighted) +
    aggregate + FetchSGD momentum/error + unsketch_topk — i.e. the entire
    sketch algebra including the d-length median query. Each phase runs as
    its own in-jit lax.scan chain with a real data dependency and ONE
    device_get sync; never raises."""
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.federated import engine

    out: dict = {}
    try:
        client_p, server_p = engine.make_split_round_step(loss_fn, cfg)
        lr = jnp.float32(0.01)

        def client_chain(st, b, rng, n):
            def body(carry, i):
                w, _, met, _ = client_p(carry, b, lr, jax.random.fold_in(rng, i))
                pflat, unravel = ravel_pytree(carry["params"])
                nxt = dict(carry)
                nxt["params"] = unravel(pflat - lr * w)  # real SGD dependency
                return nxt, met["loss_sum"]

            final, _ = jax.lax.scan(body, st, jnp.arange(n))
            return ravel_pytree(final["params"])[0][0]

        def server_chain(st, w0, rng, n):
            def body(carry, _):
                cst, w = carry
                new = server_p(cst, w, cst["net_state"], jnp.float32(NUM_WORKERS),
                               lr, rng)
                # next round's reduced update = -delta (k-sparse but dense-
                # shaped): a real dependency at realistic magnitude
                w2 = ravel_pytree(new["params"])[0] - ravel_pytree(cst["params"])[0]
                return (new, w2), ()

            (final, _), _ = jax.lax.scan(body, (st, w0), None, length=n)
            return ravel_pytree(final["params"])[0][0]

        def time_chain(label, f, *args):
            # RTT-adaptive like every other timer here: the flagship's client
            # phase is ~1/10 of a 70 ms round, so a fixed 6-iteration chain
            # would sit below one tunnel round-trip and clamp to 0 — the
            # exact failure the phase split exists to rule out.
            per, n, rtt_dominated = _time_adaptive(
                lambda n: (lambda *a: f(*a, n)), args, PHASE_CHAIN, rt_ms)
            if rtt_dominated:
                out.setdefault("rtt_dominated", []).append(label)
            return per, n

        rng = jax.random.PRNGKey(5)
        st = jax.tree.map(jnp.copy, state)
        client_ms, n_client = time_chain("client", client_chain, st, batch, rng)
        out["client_ms"] = round(client_ms, 2)
        d = cfg.mode.d
        w0 = jax.random.normal(jax.random.PRNGKey(6), (d,), jnp.float32) * 1e-3
        st2 = jax.tree.map(jnp.copy, state)
        server_ms, n_server = time_chain("server", server_chain, st2, w0, rng)
        out["server_ms"] = round(server_ms, 2)
        out["chain_len"] = {"client": n_client, "server": n_server}
        out["note"] = ("server_ms = sketch accumulate + FetchSGD algebra + "
                       "unsketch_topk over d (the suspected wall at GPT-2 "
                       "dims); client_ms = vmapped fwd/bwd + reduce")
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _baseline_basis(rt_ms) -> dict:
    """Measure ONE simulated client's cost on THIS chip in f32 (the
    reference's per-client unit of work, which its single-GPU workers run
    sequentially): ResNet-9 fwd+bwd at batch 8, or GPT-2-small fwd+bwd at
    this bench's seqs-per-client. Publishes the arithmetic that turns it
    into the vs_baseline denominator. Never raises."""
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    out: dict = {
        "reference_client_updates_per_sec": REFERENCE_CLIENT_UPDATES_PER_SEC,
        "reference_derivation": REFERENCE_DERIVATION,
    }
    try:
        if BENCH_MODEL == "resnet9":
            from commefficient_tpu.models.losses import make_classification_loss
            from commefficient_tpu.models.resnet9 import ResNet9

            model = ResNet9(num_classes=10, dtype="float32")
            x0 = jnp.zeros((1, 32, 32, 3), jnp.float32)
            variables = model.init(jax.random.PRNGKey(0), x0, train=False)
            params = variables["params"]
            net_state = {k: v for k, v in variables.items() if k != "params"}
            loss_fn = make_classification_loss(model, train=True)
            batch = {
                "x": jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3)),
                "y": jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 10),
                "mask": jnp.ones((8,), jnp.float32),
            }
            unit = "f32_b8"
        else:  # gpt2: one client = LOCAL_BATCH sequences of BENCH_SEQ tokens
            from commefficient_tpu.models.losses import make_lm_loss

            if not REFERENCE_CLIENT_UPDATES_PER_SEC:
                # tiny smoke size: no comparable reference, no serial ratio
                return {"skipped": REFERENCE_DERIVATION}
            cfg, model, seq, _ = _gpt2_model("float32")
            ids0 = jnp.zeros((1, seq), dtype=jnp.int32)
            params = model.init(jax.random.PRNGKey(0), ids0, train=False)["params"]
            net_state = {}
            loss_fn = make_lm_loss(model, train=True)
            ids = jax.random.randint(
                jax.random.PRNGKey(1), (LOCAL_BATCH, seq), 0,
                cfg.vocab_size, jnp.int32)
            batch = {"input_ids": ids, "labels": ids}
            unit = f"f32_seqs{LOCAL_BATCH}x{seq}"
        def chain(p, n):
            def body(carry, i):
                g = jax.grad(
                    lambda q: loss_fn(q, net_state, batch, jax.random.PRNGKey(0))[0]
                )(carry)
                return jax.tree.map(lambda a, b: a - 1e-3 * b, carry, g), ()

            final, _ = jax.lax.scan(body, p, jnp.arange(n))
            return ravel_pytree(final)[0][0]

        ms, n, rtt_dominated = _time_adaptive(
            lambda n: (lambda p: chain(p, n)), (params,), 10, rt_ms)
        out["chain_len"] = n
        if rtt_dominated:
            # this value becomes a denominator below — an error beats a lie
            raise RuntimeError("chain never dwarfed the tunnel RTT; "
                               "measurement would be jitter, not compute")
        out[f"measured_single_client_fwd_bwd_ms_{unit}"] = round(ms, 3)
        out["single_client_updates_per_sec_this_chip_f32"] = round(1e3 / ms, 4)
        out["chip_vs_reference_serial_ratio"] = round(
            (1e3 / ms) / REFERENCE_CLIENT_UPDATES_PER_SEC, 6)
        out["note"] = ("vs_baseline = engine updates/s / "
                       f"{REFERENCE_CLIENT_UPDATES_PER_SEC:g}; the serial "
                       "ratio above isolates the hardware factor, so "
                       "(vs_baseline / ratio) is the engine's batching/"
                       "parallelism contribution")
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _run_loop_bench(round_ms: float) -> dict:
    """Sync-vs-async run-loop comparison on a real FederatedSession at the
    flagship dims: synthetic CIFAR-shaped shards feed the session's actual
    host path (sample_clients -> native batch assembly -> dispatch ->
    metrics -> comm bookkeeping) through runner.run_loop. One session serves
    both arms back-to-back (same compiled step, warm), so the ONLY
    difference is the loop discipline. `host_overhead_ms` = wall-clock round
    minus `round_ms` (the compiled+queued round from the timed chains); the
    async loop's should sit measurably below the sync loop's. Never
    raises."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated.api import FederatedSession, FedOptimizer
    from commefficient_tpu.modes.config import ModeConfig
    from commefficient_tpu.resilience import FaultPlan
    from commefficient_tpu.runner import RunnerConfig, run_loop

    out: dict = {"rounds_per_arm": RUN_LOOP_ROUNDS}
    try:
        params, net_state, _, loss_fn, _, sketch_kw, workers = _resnet9_workload()
        from jax.flatten_util import ravel_pytree

        d = ravel_pytree(params)[0].size
        rng = np.random.RandomState(0)
        n_examples = max(512, workers * LOCAL_BATCH * 4)
        x = rng.randn(n_examples, 32, 32, 3).astype(np.float32)
        y = rng.randint(0, 10, size=n_examples).astype(np.int32)
        train_set = FedDataset(
            x, y, shard_iid(n_examples, max(2 * workers, 8),
                            np.random.RandomState(1))
        )
        fault_plan = FaultPlan.parse(BENCH_FAULT_PLAN)
        if fault_plan is not None:
            stripped = [s.kind for s in fault_plan.specs
                        if s.kind in ("preempt", "host_preempt")]
            if stripped:
                fault_plan.specs = [
                    s for s in fault_plan.specs
                    if s.kind not in ("preempt", "host_preempt")
                ]
                out["fault_plan_note"] = (
                    "preempt/host_preempt specs stripped: a SIGTERM would "
                    "exit the bench resumably instead of emitting its JSON "
                    "line"
                )
        mode_cfg = ModeConfig(
            mode="sketch", d=d, momentum_type="virtual", error_type="virtual",
            topk_impl=os.environ.get("BENCH_TOPK_IMPL", "approx"),
            topk_recall=float(os.environ.get("BENCH_TOPK_RECALL", 0.99)),
            **sketch_kw,
        )
        session = FederatedSession(
            train_loss_fn=loss_fn,
            eval_loss_fn=loss_fn,
            params=jax.tree.map(jnp.copy, params),
            net_state=jax.tree.map(jnp.copy, net_state),
            mode_cfg=mode_cfg,
            train_set=train_set,
            num_workers=workers,
            local_batch_size=LOCAL_BATCH,
            weight_decay=5e-4,
            seed=0,
            split_compile=BENCH_ENGINE_COMPILE == "split",
            on_nonfinite=os.environ.get("BENCH_ON_NONFINITE", "skip"),
            fault_plan=fault_plan,
            # BENCH_CLIENT_UPDATE_CLIP arms the sketch-space quarantine so
            # client_poison chaos benchmarks show per-client rejection cost
            client_update_clip=float(
                os.environ.get("BENCH_CLIENT_UPDATE_CLIP", "0")),
        )
        opt = FedOptimizer(lambda _: 0.01, 1)

        def arm(sync: bool, rounds: int):
            cfg = RunnerConfig(
                total_rounds=session.round + rounds,
                eval_every=session.round + rounds,  # boundaries only at end
                sync_loop=sync,
            )
            return run_loop(session, opt, cfg)

        arm(sync=True, rounds=min(2, RUN_LOOP_ROUNDS))  # compile + warm
        nonfinite = 0
        cohort = {"clients_dropped": 0, "clients_quarantined": 0,
                  "degraded_rounds": 0, "requeue_depth_max": 0,
                  "attacks_injected": 0}
        for label, sync in (("sync", True), ("async", False)):
            stats = arm(sync, RUN_LOOP_ROUNDS)
            wall_round_ms = stats.wall_s * 1e3 / max(stats.rounds, 1)
            nonfinite += stats.nonfinite_rounds
            cohort["clients_dropped"] += stats.clients_dropped
            cohort["clients_quarantined"] += stats.clients_quarantined
            cohort["degraded_rounds"] += stats.degraded_rounds
            cohort["attacks_injected"] += stats.attacks_injected
            cohort["requeue_depth_max"] = max(
                cohort["requeue_depth_max"], stats.requeue_depth_max)
            out[label] = {
                "wall_clock_updates_per_sec": round(
                    workers * stats.rounds / max(stats.wall_s, 1e-9), 2),
                "wall_round_ms": round(wall_round_ms, 2),
                "host_overhead_ms": round(wall_round_ms - round_ms, 2),
                "drains": stats.drains,
            }
        out["nonfinite_rounds"] = nonfinite
        # degradation cost of a chaos run, in the open: how many clients the
        # masking/quarantine machinery absorbed while the numbers above were
        # produced (all zero without BENCH_FAULT_PLAN)
        out["cohort"] = cohort
        out["async_speedup_vs_sync"] = round(
            out["sync"]["wall_round_ms"] / max(out["async"]["wall_round_ms"],
                                               1e-9), 3)
        out["note"] = (
            "one session, arms run back-to-back on the warm compiled step; "
            "host_overhead_ms = wall-clock round - round_ms (the chained "
            "compiled round), i.e. what the host costs on top of the device"
        )
        # tracing overhead: one more async arm with the obs tracer armed
        # (same warm session), vs the untraced async arm above — the
        # contract is spans-without-syncs, so this should sit under ~2%
        import tempfile

        from commefficient_tpu.obs import trace as obtrace

        trace_path = os.path.join(tempfile.mkdtemp(prefix="bench_obs_"),
                                  "trace.json")
        obtrace.configure(trace_path=trace_path)
        try:
            t_stats = arm(sync=False, rounds=RUN_LOOP_ROUNDS)
            n_events = obtrace.get().event_count()
        finally:
            obtrace.configure()  # disarm (drops the buffer; no file needed)
        traced_ms = t_stats.wall_s * 1e3 / max(t_stats.rounds, 1)
        untraced_ms = out["async"]["wall_round_ms"]
        out["obs"] = {
            "untraced_wall_round_ms": untraced_ms,
            "traced_wall_round_ms": round(traced_ms, 2),
            "tracing_overhead_pct": round(
                100.0 * (traced_ms - untraced_ms) / max(untraced_ms, 1e-9),
                2),
            "trace_events_per_round": round(
                n_events / max(t_stats.rounds, 1), 1),
            "note": "async arm re-run with --trace armed; expected < 2% "
                    "overhead (host-side timestamps only, no added syncs)",
        }
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _sketch_path_bench(round_ms: float) -> dict:
    """--sketch_path ravel vs layerwise on the run_loop bench: one warm
    FederatedSession per arm (same seed, same synthetic shards, same
    compiled-arm discipline as _run_loop_bench), driven through the REAL
    async runner — wall-clock updates/s and per-round ms per arm — plus the
    HBM headline: peak live-buffer bytes of each arm's compiled fused round
    program (XLA memory_analysis; temp + output bytes — the buffers the
    program itself owns; argument bytes excluded, both arms bind the same
    params/batch). The layerwise arm never materializes the flat [d]
    gradient, so its peak should sit strictly below ravel's at matched
    dims. Also re-confirms the obs contract on the NEW arm: tracing the
    layerwise run adds < ~2%. Never raises."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated import engine
    from commefficient_tpu.federated.api import FederatedSession, FedOptimizer
    from commefficient_tpu.modes.config import ModeConfig
    from commefficient_tpu.runner import RunnerConfig, run_loop

    rounds = RUN_LOOP_ROUNDS
    out: dict = {"rounds_per_arm": rounds}
    try:
        params, net_state, _, loss_fn, _, sketch_kw, workers = _resnet9_workload()
        from jax.flatten_util import ravel_pytree

        d = ravel_pytree(params)[0].size
        out["d"] = d
        rng = np.random.RandomState(0)
        n_examples = max(512, workers * LOCAL_BATCH * 4)
        x = rng.randn(n_examples, 32, 32, 3).astype(np.float32)
        y = rng.randint(0, 10, size=n_examples).astype(np.int32)

        def make_session(sketch_path):
            return FederatedSession(
                train_loss_fn=loss_fn,
                eval_loss_fn=loss_fn,
                params=jax.tree.map(jnp.copy, params),
                net_state=jax.tree.map(jnp.copy, net_state),
                mode_cfg=ModeConfig(
                    mode="sketch", d=d, momentum_type="virtual",
                    error_type="virtual",
                    topk_impl=os.environ.get("BENCH_TOPK_IMPL", "approx"),
                    topk_recall=float(
                        os.environ.get("BENCH_TOPK_RECALL", 0.99)),
                    **sketch_kw,
                ),
                train_set=FedDataset(
                    x, y, shard_iid(n_examples, max(2 * workers, 8),
                                    np.random.RandomState(1))),
                num_workers=workers,
                local_batch_size=LOCAL_BATCH,
                weight_decay=5e-4,
                seed=0,
                split_compile=BENCH_ENGINE_COMPILE == "split",
                sketch_path=sketch_path,
            )

        def arm(session, sync, n):
            cfg = RunnerConfig(
                total_rounds=session.round + n,
                eval_every=session.round + n,
                sync_loop=sync,
            )
            return run_loop(session, FedOptimizer(lambda _: 0.01, 1), cfg)

        # ---- peak live-buffer bytes of the compiled fused round program.
        # Abstract batch from a throwaway session's real prepared round, so
        # the analyzed program binds exactly what the timed arms bind.
        probe = make_session("ravel")
        prep = probe.prepare_round(0)
        batch_abs = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape,
                                           np.asarray(a).dtype),
            dict(prep.batch))
        import dataclasses as _dc

        mem = {}
        for label in ("ravel", "layerwise"):
            cfg = _dc.replace(probe.cfg, sketch_path=label)
            step = jax.jit(engine.make_round_step(loss_fn, cfg))
            state = engine.init_server_state(
                cfg, jax.tree.map(jnp.copy, params),
                jax.tree.map(jnp.copy, net_state))
            try:
                ma = step.lower(
                    state, batch_abs, {},
                    jax.ShapeDtypeStruct((), np.float32),
                    jax.random.PRNGKey(0),
                ).compile().memory_analysis()
                mem[label] = {
                    "temp_bytes": int(ma.temp_size_in_bytes),
                    "output_bytes": int(ma.output_size_in_bytes),
                    "argument_bytes": int(ma.argument_size_in_bytes),
                    "peak_live_buffer_bytes": int(
                        ma.temp_size_in_bytes + ma.output_size_in_bytes),
                }
            except Exception as e:  # noqa: BLE001 — degrade to skipped
                mem[label] = {"skipped": f"memory_analysis unavailable: "
                                         f"{type(e).__name__}: {e}"}
        out["memory"] = mem
        if all("peak_live_buffer_bytes" in m for m in mem.values()):
            delta = (mem["ravel"]["peak_live_buffer_bytes"]
                     - mem["layerwise"]["peak_live_buffer_bytes"])
            out["memory"]["peak_live_buffer_bytes_delta"] = delta
            out["memory"]["note"] = (
                "delta = ravel - layerwise peak (temp + output) of the "
                "compiled fused round program; positive = the layerwise "
                "arm's live set is smaller (no flat [d] gradient, no flat "
                "params copy)")

        # ---- timed arms through the real async runner, warm
        for label in ("ravel", "layerwise"):
            session = make_session(label)
            arm(session, sync=True, n=min(2, rounds))  # compile + warm
            stats = arm(session, sync=False, n=rounds)
            wall_round_ms = stats.wall_s * 1e3 / max(stats.rounds, 1)
            out[label] = {
                "wall_clock_updates_per_sec": round(
                    workers * stats.rounds / max(stats.wall_s, 1e-9), 2),
                "wall_round_ms": round(wall_round_ms, 2),
                "host_overhead_ms": round(wall_round_ms - round_ms, 2),
            }
            if label == "layerwise":
                # obs re-confirmation on the NEW arm: the deferred
                # device-phase spans (now carrying sketch_path=) still add
                # zero syncs — expect < ~2% like the ravel run_loop arm
                import tempfile

                from commefficient_tpu.obs import trace as obtrace

                obtrace.configure(trace_path=os.path.join(
                    tempfile.mkdtemp(prefix="bench_lw_obs_"), "trace.json"))
                try:
                    t_stats = arm(session, sync=False, n=rounds)
                finally:
                    obtrace.configure()
                traced_ms = t_stats.wall_s * 1e3 / max(t_stats.rounds, 1)
                out["obs"] = {
                    "untraced_wall_round_ms": round(wall_round_ms, 2),
                    "traced_wall_round_ms": round(traced_ms, 2),
                    "tracing_overhead_pct": round(
                        100.0 * (traced_ms - wall_round_ms)
                        / max(wall_round_ms, 1e-9), 2),
                    "note": "layerwise async arm re-run with --trace armed; "
                            "device spans carry sketch_path=layerwise",
                }
        if "wall_round_ms" in out.get("ravel", {}):
            out["layerwise_vs_ravel_round_ms_ratio"] = round(
                out["layerwise"]["wall_round_ms"]
                / max(out["ravel"]["wall_round_ms"], 1e-9), 3)
    except Exception as e:  # noqa: BLE001 — the stanza IS the result
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _health_bench() -> dict:
    """The obs.health arm: (a) estimator overhead — the SAME flagship
    workload with --health_every 1 vs health off, both warm, through the
    real async runner (the in-program estimators add one unsketch + one
    dense top-k per round under the cadence cond; expected < ~2% like
    tracing); (b) the recall-proxy VALIDATION on the dense-comparable
    config — the fused ravel path computes both `topk_mass_proxy` (from
    the wire table alone) and `topk_mass_true` (from the dense reduced
    update the simulator still has), and the acceptance bar is agreement
    within 0.05. The geometry keeps k/c <= ~1/16 (BENCH_HEALTH_COLS
    overrides): past that the collision bias the proxy exists to DETECT
    dominates — row_mass_cv is the saturation gauge there. Never
    raises."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated.api import FederatedSession, FedOptimizer
    from commefficient_tpu.modes.config import ModeConfig
    from commefficient_tpu.obs.health import HealthMonitor
    from commefficient_tpu.runner import RunnerConfig, run_loop

    rounds = HEALTH_ROUNDS
    cols = int(os.environ.get("BENCH_HEALTH_COLS",
                              max(SKETCH_COLS, 16 * TOPK)))
    out: dict = {"rounds_per_arm": rounds,
                 "geometry": {"rows": SKETCH_ROWS, "cols": cols, "k": TOPK}}
    try:
        params, net_state, _, loss_fn, _, sketch_kw, workers = _resnet9_workload()
        from jax.flatten_util import ravel_pytree

        d = ravel_pytree(params)[0].size
        out["d"] = d
        rng = np.random.RandomState(0)
        n_examples = max(512, workers * LOCAL_BATCH * 4)
        x = rng.randn(n_examples, 32, 32, 3).astype(np.float32)
        y = rng.randint(0, 10, size=n_examples).astype(np.int32)
        kw = dict(sketch_kw)
        kw["num_cols"] = cols

        def make_session(health_every):
            return FederatedSession(
                train_loss_fn=loss_fn,
                eval_loss_fn=loss_fn,
                params=jax.tree.map(jnp.copy, params),
                net_state=jax.tree.map(jnp.copy, net_state),
                mode_cfg=ModeConfig(
                    mode="sketch", d=d, momentum_type="virtual",
                    error_type="virtual", **kw,
                ),
                train_set=FedDataset(
                    x, y, shard_iid(n_examples, max(2 * workers, 8),
                                    np.random.RandomState(1))),
                num_workers=workers,
                local_batch_size=LOCAL_BATCH,
                weight_decay=5e-4,
                seed=0,
                health_every=health_every,
            )

        def arm(session, sync, n):
            cfg = RunnerConfig(
                total_rounds=session.round + n,
                eval_every=session.round + n,
                sync_loop=sync,
            )
            return run_loop(session, FedOptimizer(lambda _: 0.01, 1), cfg)

        walls = {}
        monitor = None
        for label, every in (("off", 0), ("on", 1)):
            session = make_session(every)
            arm(session, sync=True, n=min(2, rounds))  # compile + warm
            if every:
                # attached AFTER the warm arm so the recorded history is
                # exactly the timed rounds
                monitor = HealthMonitor(
                    mode_cfg=session.cfg.mode, num_workers=workers,
                    health_every=every)
                session.health_monitor = monitor
            stats = arm(session, sync=False, n=rounds)
            walls[label] = stats.wall_s * 1e3 / max(stats.rounds, 1)
            out[f"{label}_wall_round_ms"] = round(walls[label], 2)
        out["estimator_overhead_pct"] = round(
            100.0 * (walls["on"] - walls["off"]) / max(walls["off"], 1e-9),
            2)
        proxy = monitor.series("topk_mass_proxy")
        true = monitor.series("topk_mass_true")
        diffs = [abs(p - t) for p, t in zip(proxy, true)]
        out["recall_proxy"] = {
            "health_rounds": len(proxy),
            "proxy_mean": round(float(np.mean(proxy)), 4) if proxy else None,
            "true_mean": round(float(np.mean(true)), 4) if true else None,
            "max_abs_diff": round(max(diffs), 4) if diffs else None,
            "mean_abs_diff": round(float(np.mean(diffs)), 4) if diffs
            else None,
            "within_0_05": bool(diffs and max(diffs) <= 0.05),
        }
        out["saturation"] = {
            "row_mass_cv_mean": round(float(np.mean(
                monitor.series("row_mass_cv") or [0.0])), 4),
            "table_occupancy_mean": round(float(np.mean(
                monitor.series("table_occupancy") or [0.0])), 4),
        }
        out["note"] = (
            "overhead = health_every=1 vs health-off wall round on the "
            "warm async runner (both identical bits — the estimators only "
            "read); the estimator cost is O(r*d) per HEALTH round, so the "
            "percentage scales inversely with the cohort's compute (the "
            "flagship W-client fwd/bwd dwarfs it; toy dims inflate it — "
            "raise --health_every to amortize); recall_proxy compares the "
            "wire-side top-k energy fraction estimate against the "
            "dense-path truth per health round (the SketchedSGD "
            "accuracy-vs-compression observable)"
        )
    except Exception as e:  # noqa: BLE001 — the stanza IS the result
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _byzantine_bench() -> dict:
    """Final-accuracy under each adversarial client kind x merge policy on
    the flagship (ResNet-9, separable synthetic CIFAR so accuracy moves in
    few rounds), plus the merge-policy overhead in updates/s on a clean
    run — the price of forfeiting the compress-once linearity shortcut.
    Never raises; partial arms still report."""
    import time as _time

    import numpy as np

    import jax
    import jax.numpy as jnp

    from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
    from commefficient_tpu.federated.api import FederatedSession
    from commefficient_tpu.modes.config import ModeConfig
    from commefficient_tpu.resilience import FaultPlan

    rounds = BYZANTINE_ROUNDS
    out: dict = {"rounds_per_arm": rounds}
    try:
        params, net_state, _, loss_fn, _, sketch_kw, workers = _resnet9_workload()
        from jax.flatten_util import ravel_pytree

        d = ravel_pytree(params)[0].size
        rng = np.random.RandomState(0)
        n_examples = max(512, workers * LOCAL_BATCH * 4)
        # separable synthetic CIFAR (class prototypes + noise): accuracy
        # responds within BYZANTINE_ROUNDS, so attack damage is visible
        protos = rng.randn(10, 32, 32, 3).astype(np.float32)
        y = rng.randint(0, 10, size=n_examples).astype(np.int32)
        x = (protos[y]
             + 0.5 * rng.randn(n_examples, 32, 32, 3)).astype(np.float32)

        # a one-client sign-flipper, a 20x model-replacement scaler, and a
        # seeded ~12% colluding-clone minority — each on every round
        all_rounds = ",".join(str(r) for r in range(rounds))
        trim = max(1, int(np.ceil(0.12 * workers)))
        attacks = {
            "none": None,
            "signflip": f"client_signflip@{all_rounds}:clients=0",
            "scale": f"client_scale@{all_rounds}:clients=0,factor=20",
            "collude": f"client_collude@{all_rounds}:frac=0.12",
        }
        # the sum arms run wire_payloads=True so EVERY cell of the grid —
        # clean included — executes the per-client-table round: the
        # attacked-vs-clean deltas are attack damage, never the documented
        # fp-association gap between the table and compress-once shapes
        policies = {"sum": {"wire_payloads": True},
                    "trimmed": {"merge_trim": trim}, "median": {}}
        out["merge_trim"] = trim

        def make_session(policy, plan_text, **kw):
            return FederatedSession(
                train_loss_fn=loss_fn, eval_loss_fn=loss_fn,
                params=jax.tree.map(jnp.copy, params),
                net_state=jax.tree.map(jnp.copy, net_state),
                mode_cfg=ModeConfig(
                    mode="sketch", d=d, momentum_type="virtual",
                    error_type="virtual",
                    topk_impl=os.environ.get("BENCH_TOPK_IMPL", "approx"),
                    topk_recall=float(
                        os.environ.get("BENCH_TOPK_RECALL", 0.99)),
                    **sketch_kw),
                train_set=FedDataset(
                    x, y, shard_iid(n_examples, max(2 * workers, 8),
                                    np.random.RandomState(1))),
                num_workers=workers, local_batch_size=LOCAL_BATCH,
                weight_decay=5e-4, seed=0, merge_policy=policy,
                fault_plan=FaultPlan.parse(plan_text), **kw)

        acc = {}
        # assigned BEFORE the grid runs (and mutated in place), so a
        # mid-grid failure still reports every completed arm
        out["accuracy"] = acc
        for aname, plan_text in attacks.items():
            acc[aname] = {}
            for pname, pkw in policies.items():
                s = make_session(pname, plan_text, **pkw)
                t0 = _time.perf_counter()
                ms = [s.run_round(0.02) for _ in range(rounds)]
                wall = _time.perf_counter() - t0
                tail = ms[max(0, rounds - 3):]
                correct = sum(m.get("correct", 0.0) for m in tail)
                count = max(sum(m.get("count", 0.0) for m in tail), 1.0)
                arm = {"final_train_acc": round(correct / count, 4),
                       "final_train_loss": round(
                           tail[-1].get("loss_sum", float("nan"))
                           / max(tail[-1].get("count", 0.0), 1.0), 4)}
                if aname == "none":
                    # clean arms double as the merge-policy overhead probe
                    # (wall includes the compile; report post-warm rate too)
                    t1 = _time.perf_counter()
                    extra = max(2, rounds // 4)
                    for _ in range(extra):
                        s.run_round(0.02)
                    warm = _time.perf_counter() - t1
                    arm["updates_per_sec_warm"] = round(
                        workers * extra / max(warm, 1e-9), 2)
                    arm["wall_s_incl_compile"] = round(wall, 2)
                acc[aname][pname] = arm
                _stage(f"byzantine {aname} x {pname}: {arm}")
        clean = acc.get("none", {})
        if all("updates_per_sec_warm" in clean.get(p, {})
               for p in ("sum", "trimmed", "median")):
            base = clean["sum"]["updates_per_sec_warm"]
            out["merge_policy_overhead"] = {
                p: {"updates_per_sec_warm":
                        clean[p]["updates_per_sec_warm"],
                    "vs_sum": round(
                        clean[p]["updates_per_sec_warm"] / max(base, 1e-9),
                        3)}
                for p in ("sum", "trimmed", "median")}
        # async arm: the robust-merge overhead on the BUFFERED path — the
        # per-buffer robust merge (order statistics over {current buffer +
        # staleness-weighted stale folds}) vs the linear stale fold, both
        # through the real serving stack (inproc transport, buffer-trigger
        # closes, stragglers folding staleness-weighted into later merges)
        try:
            from commefficient_tpu.obs import registry as _obreg
            from commefficient_tpu.serve.service import (
                AggregationService, ServeConfig)
            from commefficient_tpu.serve.traffic import (
                TraceConfig, TrafficGenerator)

            a_rounds = max(rounds // 2, 4)
            trigger = max(workers * 3 // 4, 2)
            reg = _obreg.default()
            async_out: dict = {}
            for pname, pkw in (("sum", {}),
                               ("trimmed", {"merge_policy": "trimmed",
                                            "merge_trim": trim})):
                s = make_session(pkw.pop("merge_policy", "sum"), None,
                                 wire_payloads=True, stale_slots=workers,
                                 **pkw)
                svc = AggregationService(
                    s, ServeConfig(quorum=workers, deadline_s=60.0,
                                   payload="sketch", async_mode=True,
                                   buffer_size=trigger),
                    traffic=TrafficGenerator(TraceConfig(
                        population=s.train_set.num_clients,
                        seed=7))).start()
                try:
                    src = svc.source()
                    base_folded = reg.counter(
                        "serve_stale_folded_total").value
                    t0 = _time.perf_counter()
                    for _ in range(a_rounds):
                        prep = src.next()
                        s.commit_round(s.dispatch_round(prep, 0.02))
                        src.on_dispatched(s.round - 1)
                        src.on_committed(s.round)
                    src.stop()
                    wall = _time.perf_counter() - t0
                    async_out[pname] = {
                        "rounds_per_sec": round(a_rounds / max(wall, 1e-9),
                                                3),
                        "stale_folded": int(reg.counter(
                            "serve_stale_folded_total").value
                            - base_folded),
                        "wall_s_incl_compile": round(wall, 2),
                    }
                finally:
                    svc.close()
            if "sum" in async_out and "trimmed" in async_out:
                base = async_out["sum"]["rounds_per_sec"]
                async_out["trimmed"]["vs_sum"] = round(
                    async_out["trimmed"]["rounds_per_sec"]
                    / max(base, 1e-9), 3)
            async_out["buffer_size"] = trigger
            async_out["rounds_per_arm"] = a_rounds
            out["async"] = async_out
            _stage(f"byzantine async arm: {async_out}")
        except Exception as e:  # noqa: BLE001 — partial arms still report
            out["async"] = {"error": f"{type(e).__name__}: {e}"}
        out["note"] = (
            "accuracy = train accuracy over the last 3 rounds; attacks ride "
            "the per-client-table round (sum arms included, so damage is "
            "attack-caused, not shape-caused); overhead vs_sum < 1 is the "
            "robust policies' cost — the compress-once shortcut forfeited "
            "plus the per-coordinate order statistics; the async block is "
            "the BUFFERED path's twin (per-buffer robust merge vs linear "
            "stale fold through the real serving stack, wall incl compile)")
    except Exception as e:  # noqa: BLE001 — the stanza IS the result
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _scale_bench() -> dict:
    """C1M scale-out measurements (serve/scale/): transport concurrency
    ramp (threaded vs event-loop), edge-tree vs flat merge wall-clock at
    W=256, process-shard strong scaling (submissions/s vs 1/2/4/8 shard
    worker processes under the closed-loop loadgen), and the 2048->100k
    connection loadgen ramp with its fd/rlimit ceiling. Never raises;
    every arm degrades to {"skipped": ...} on its own."""
    import json as _json
    import resource
    import socket as _socket
    import time as _time

    import numpy as np

    try:
        from commefficient_tpu.serve.ingest import IngestQueue
        from commefficient_tpu.serve.scale.eventloop import EventLoopTransport
        from commefficient_tpu.serve.transport import SocketTransport
    except Exception as e:  # noqa: BLE001 — the skipped stanza IS the result
        return {"skipped": f"scale deps unavailable: {type(e).__name__}: {e}"}

    out: dict = {}
    # loopback concurrency needs fds: raise the soft limit to the hard cap
    # (each held connection is ~2 fds in-process: server side + client side)
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    # RLIM_INFINITY is -1: normalize both limbs before comparing/arithmetic
    # (an "unlimited" container must not read as a 64-conn ceiling)
    big = 1 << 20
    soft_n = big if soft == resource.RLIM_INFINITY else soft
    hard_n = big if hard == resource.RLIM_INFINITY else hard
    if soft_n < hard_n:
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
        soft_n = hard_n
    max_conns = min(SCALE_CONNS, max((soft_n - 256) // 2, 64))
    out["fd_limit"] = soft_n

    def ramp(transport_factory, label: str) -> dict:
        levels, results = [], {}
        c = 64
        while c <= max_conns:
            levels.append(c)
            c *= 2
        max_sustained, best_rate = 0, 0.0
        for level in levels:
            q = IngestQueue(capacity=max(level * 2, 1024))
            t = transport_factory(q)
            t.start()
            socks, ok = [], True
            try:
                q.open_round(0, list(range(level)))
                for _ in range(level):
                    try:
                        socks.append(_socket.create_connection(
                            t.address, timeout=5.0))
                    except OSError:
                        ok = False
                        break
                if ok:
                    t0 = _time.perf_counter()
                    for i, s in enumerate(socks):
                        try:
                            s.sendall(_json.dumps(
                                {"client_id": i, "round": 0,
                                 "latency_s": 0.1}).encode() + b"\n")
                        except OSError:
                            ok = False
                    got = 0
                    for s in socks:
                        try:
                            s.settimeout(30.0)
                            buf = b""
                            while b"\n" not in buf:
                                chunk = s.recv(4096)
                                if not chunk:
                                    break
                                buf += chunk
                            if b"ACCEPTED" in buf:
                                got += 1
                        except OSError:
                            pass
                    wall = _time.perf_counter() - t0
                    rate = round(got / max(wall, 1e-9), 1)
                    results[str(level)] = {
                        "held": len(socks), "accepted": got,
                        "submissions_per_sec": rate,
                    }
                    if got == level:
                        max_sustained = level
                        best_rate = max(best_rate, rate)
                    else:
                        break
                else:
                    results[str(level)] = {"held": len(socks),
                                           "accepted": 0,
                                           "submissions_per_sec": 0.0}
                    break
            finally:
                for s in socks:
                    try:
                        s.close()
                    except OSError:
                        pass
                t.stop()
                q.shutdown()
        return {"levels": results, "max_sustained_conns": max_sustained,
                "best_submissions_per_sec": best_rate, "label": label}

    try:
        threaded = ramp(lambda q: SocketTransport(q, read_deadline_s=60.0),
                        "threaded (1 thread/conn, capped)")
        eventloop = ramp(
            lambda q: EventLoopTransport(q, read_deadline_s=60.0),
            "eventloop (1 reactor thread)")
        ratio = (eventloop["max_sustained_conns"]
                 / max(threaded["max_sustained_conns"], 1))
        out["transport_concurrency"] = {
            "threaded": threaded, "eventloop": eventloop,
            "eventloop_over_threaded": round(ratio, 2),
            # the acceptance bar: the reactor holds >= 10x the threaded
            # transport's concurrent connections on this box
            "meets_10x": bool(ratio >= 10.0),
        }
    except Exception as e:  # noqa: BLE001 — degrade per sub-arm
        out["transport_concurrency"] = {
            "skipped": f"{type(e).__name__}: {e}"}

    # (b) edge-tree vs flat merge wall-clock at W=256: real served payload
    # sessions over a small quadratic model (the arm measures the MERGE
    # topology, not the model) — same cohort, same trace, edges=8 vs flat
    try:
        import collections as _collections

        import jax
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree

        from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
        from commefficient_tpu.federated.api import FederatedSession
        from commefficient_tpu.modes.config import ModeConfig
        from commefficient_tpu.serve.service import (
            AggregationService, ServeConfig)
        from commefficient_tpu.serve.traffic import (
            TraceConfig, TrafficGenerator)

        W = 256

        def quad_loss(params, net_state, batch, rng):
            pred = batch["x"] @ params["w"] + params["b"]
            err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
            mask = batch["mask"]
            per_ex = (err ** 2).sum(-1)
            return (per_ex * mask).sum() / jnp.maximum(mask.sum(), 1.0), {
                "net_state": net_state,
                "metrics": {"loss_sum": (per_ex * mask).sum(),
                            "count": mask.sum()}}

        def build(serve_edges):
            rs = np.random.RandomState(0)
            x = rs.randn(2048, 8).astype(np.float32)
            y = rs.randint(0, 4, size=2048).astype(np.int32)
            train = FedDataset(
                x, y, shard_iid(len(x), 512, np.random.RandomState(1)))
            params = {"w": jnp.asarray(
                rs.randn(8, 4).astype(np.float32) * 0.1),
                "b": jnp.zeros(4)}
            d = ravel_pytree(params)[0].size
            mc = ModeConfig(mode="sketch", d=d, k=8, num_rows=3,
                            num_cols=16, momentum_type="virtual",
                            error_type="virtual")
            return FederatedSession(
                train_loss_fn=quad_loss, eval_loss_fn=quad_loss,
                params=params, net_state={}, mode_cfg=mc, train_set=train,
                num_workers=W, local_batch_size=4, seed=0,
                wire_payloads=True, serve_edges=serve_edges)

        def run(serve_edges, edges):
            session = build(serve_edges)
            cfg = ServeConfig(quorum=W * 3 // 4, transport="inproc",
                              payload="sketch", edges=edges)
            svc = AggregationService(
                session, cfg,
                traffic=TrafficGenerator(
                    TraceConfig(population=512, seed=9))).start()
            try:
                src = svc.source()
                # one warmup (compiles), then timed rounds
                prep = src.next()
                session.commit_round(session.dispatch_round(prep, 0.05))
                src.on_dispatched(session.round - 1)
                src.on_committed(session.round)
                t0 = _time.perf_counter()
                for _ in range(SCALE_ROUNDS):
                    prep = src.next()
                    session.commit_round(
                        session.dispatch_round(prep, 0.05))
                    src.on_dispatched(session.round - 1)
                    src.on_committed(session.round)
                wall = _time.perf_counter() - t0
                src.stop()
                with session.mutate_lock:
                    rng_state, rng_key = session.rng_snapshot
                    session.rng.set_state(rng_state)
                    session._rng_key = rng_key
                    session._requeue = _collections.deque(
                        session._requeue_committed)
                    session._requeue_enqueued = dict(
                        session._requeue_ages_committed)
            finally:
                svc.close()
            return {"rounds": SCALE_ROUNDS,
                    "round_ms": round(wall / SCALE_ROUNDS * 1e3, 2),
                    "rounds_per_sec": round(SCALE_ROUNDS / wall, 3)}

        flat = run(8, 0)     # grouped program, no tree (the parity twin)
        tree = run(8, 8)     # the 8-edge two-tier topology
        out["edge_vs_flat"] = {
            "cohort": W, "edges": 8,
            "flat": flat, "edge_tree": tree,
            "edge_over_flat_round_ms": round(
                tree["round_ms"] / max(flat["round_ms"], 1e-9), 3),
        }
    except Exception as e:  # noqa: BLE001 — degrade per sub-arm
        out["edge_vs_flat"] = {"skipped": f"{type(e).__name__}: {e}"}

    # (c) process-shard strong scaling: submissions/s through REAL loopback
    # sockets vs shard WORKER PROCESSES (1/2/4/8), measured from OUTSIDE the
    # server's processes by the multi-process closed-loop loadgen (flat
    # model, zero think — a capacity probe, not a traffic replay). The
    # 1-process arm is the fused single-reactor baseline the shards are
    # promoted from; the acceptance bar is >= 2x submissions/s at 4 shard
    # processes on a multi-core box. On a 1-core box the curve would
    # measure the scheduler, not the ingest — the stanza says so and skips
    # (BENCH_PROC_CURVE=1 forces it anyway, e.g. to smoke the harness).
    try:
        import os as _os

        from commefficient_tpu.serve.scale.loadgen import (
            _FD_HEADROOM, LoadGenConfig, run_ramp, run_stage)
        from commefficient_tpu.serve.scale.procshard import ProcShardedIngest

        ncpu = _os.cpu_count() or 1

        def _loadgen_ids(conns: int, procs: int, base: int) -> list:
            # mirror _loadgen_worker's id assignment (base + wid*cap + i)
            # so the round can INVITE the fleet and the verdict mix reads
            # accepted/duplicate, not a wall of UNINVITED rejections
            lg_soft = resource.getrlimit(resource.RLIMIT_NOFILE)[0]
            cap = max(int(lg_soft) - _FD_HEADROOM, 16)
            per = max(conns // procs, 1)
            shares = [per] * procs
            shares[-1] += conns - per * procs
            return [base + wid * cap + i
                    for wid, share in enumerate(shares)
                    for i in range(min(share, cap))]

        LG_PROCS = 4
        PROBE_CONNS = min(512, max_conns)
        PROBE_STAGE_S = 2.5
        BASE_ID = 1 << 20

        def probe(n_shards: int) -> dict:
            if n_shards == 1:
                q = IngestQueue(capacity=max(PROBE_CONNS * 4, 4096))
                t = EventLoopTransport(q, read_deadline_s=60.0)
            else:
                t = ProcShardedIngest(n_shards=n_shards)
                q = t.queue
            t.start()
            try:
                q.open_round(0, _loadgen_ids(PROBE_CONNS, LG_PROCS, BASE_ID))
                host, port = t.address
                stage = run_stage(LoadGenConfig(
                    host=host, port=port, connections=PROBE_CONNS,
                    processes=LG_PROCS, stage_s=PROBE_STAGE_S,
                    model="flat", think_s=0.0, ramp_start=PROBE_CONNS,
                    client_base=BASE_ID), PROBE_CONNS)
                q.close_round(0)
                return stage
            finally:
                t.stop()
                if n_shards == 1:
                    q.shutdown()

        if ncpu < 4 and _os.environ.get("BENCH_PROC_CURVE", "") != "1":
            out["proc_strong_scaling"] = {
                "skipped": (
                    f"strong-scaling curve needs >= 4 cores (nproc={ncpu}):"
                    " one core serializes the shard worker processes, so"
                    " the 1/2/4/8-process curve would measure the kernel"
                    " scheduler, not the sharded ingest. Run on a"
                    " multi-core box (or force with BENCH_PROC_CURVE=1);"
                    " the bar there is >= 2x submissions/s at 4 processes"
                    " vs the fused 1-reactor baseline"),
                "nproc": ncpu,
            }
        else:
            curve = {}
            for n in (1, 2, 4, 8):
                curve[str(n)] = probe(n)
            s1 = curve["1"]["submissions_per_s"]
            s4 = curve["4"]["submissions_per_s"]
            out["proc_strong_scaling"] = {
                "nproc": ncpu,
                "connections": PROBE_CONNS,
                "stage_s": PROBE_STAGE_S,
                "loadgen_processes": LG_PROCS,
                "shard_processes": curve,
                "speedup_4_over_1": round(s4 / max(s1, 1e-9), 2),
                # the acceptance bar (meaningful on >= 4 cores only)
                "meets_2x_at_4": bool(s4 >= 2.0 * s1),
            }
    except Exception as e:  # noqa: BLE001 — degrade per sub-arm
        out["proc_strong_scaling"] = {"skipped": f"{type(e).__name__}: {e}"}

    # (d) the 100k-connection closed-loop ramp: doubling stages from 2048
    # toward LOADGEN_CONNS against the 4-process shard ingest, stopping at
    # — and NAMING — the fd/rlimit ceiling this box actually hits (the
    # ceiling IS a result: it says what one box can hold, and why).
    try:
        ramp_target = LOADGEN_CONNS
        t = ProcShardedIngest(n_shards=4)
        t.start()
        try:
            t.queue.open_round(0, _loadgen_ids(ramp_target, 8, BASE_ID))
            host, port = t.address
            ramp = run_ramp(LoadGenConfig(
                host=host, port=port, connections=ramp_target,
                processes=8, stage_s=2.0, model="flat", think_s=0.05,
                ramp_start=2048, client_base=BASE_ID,
                connect_timeout_s=8.0), log=print)
            t.queue.close_round(0)
        finally:
            t.stop()
        out["loadgen_ramp"] = {
            "target_conns": ramp_target,
            "shard_processes": 4,
            "loadgen_processes": 8,
            **ramp,
        }
    except Exception as e:  # noqa: BLE001 — degrade per sub-arm
        out["loadgen_ramp"] = {"skipped": f"{type(e).__name__}: {e}"}
    return out


def _serve_bench() -> dict:
    """Streaming-aggregation service measurements (see the SERVE_BENCH
    comment). Never raises; {"skipped": ...} when the serving deps are
    unavailable in this environment."""
    import time as _time
    import tracemalloc

    import numpy as np

    try:
        from commefficient_tpu.serve import (
            AggregationService, IngestQueue, ServeConfig, Submission,
            TraceConfig, TrafficGenerator,
        )
    except Exception as e:  # noqa: BLE001 — the skipped stanza IS the result
        return {"skipped": f"serve deps unavailable: {type(e).__name__}: {e}"}

    out: dict = {"rounds": SERVE_ROUNDS}
    try:
        # (a) ingest throughput: the admission-control hot path alone —
        # open_round + submit over a realistic accept/reject mix from the
        # diurnal trace (uninvited pushes bounce, invited ones admit)
        trace = TraceConfig(population=10_000, base_rate=2_000.0,
                            burst_rate=0.2, burst_size=100, seed=7)
        gen = TrafficGenerator(trace)
        queue = IngestQueue(capacity=65_536, pending_capacity=1024)
        rs = np.random.RandomState(3)
        invited = rs.choice(trace.population, size=4096, replace=False)
        queue.open_round(0, invited)
        n_sub = 0
        t0 = _time.perf_counter()
        for t, ids in gen.arrival_events(6 * 3600.0, 30.0, window_s=1.0):
            for cid in ids:
                queue.submit(Submission(client_id=int(cid), round=0,
                                        latency_s=float(t)))
                n_sub += 1
        wall = _time.perf_counter() - t0
        c = queue.counters()
        out["ingest"] = {
            "submissions": n_sub,
            "submissions_per_sec": round(n_sub / max(wall, 1e-9), 1),
            "accepted_per_sec": round(c["accepted"] / max(wall, 1e-9), 1),
            "counters": c,
        }

        # (b) O(1) client-state memory: derive device classes + response
        # latencies for identical-size invite batches out of a 10k and a
        # {SERVE_POPULATION} population — peak host memory must be FLAT
        # (no per-client table anywhere on the path)
        def peak_bytes(population: int) -> int:
            g = TrafficGenerator(TraceConfig(population=population, seed=11))
            rs = np.random.RandomState(5)
            tracemalloc.start()
            for rnd in range(20):
                ids = rs.randint(0, population, size=4096)
                g.invite_latencies(rnd, ids)
            _, peak = tracemalloc.get_traced_memory()
            tracemalloc.stop()
            return peak

        small, big = peak_bytes(10_000), peak_bytes(SERVE_POPULATION)
        out["client_state_memory"] = {
            "population_small": 10_000,
            "population_big": SERVE_POPULATION,
            "peak_bytes_small": small,
            "peak_bytes_big": big,
            "big_over_small": round(big / max(small, 1), 3),
            "flat": bool(big <= 2 * small),
            "note": "per-(client,round) streams are pure fold_in functions "
                    "of (seed, id): memory scales with the invite batch, "
                    "never the population",
        }

        # (c) submission-to-merge latency through a REAL served session:
        # wall time from a submission's ACCEPT to the commit that published
        # its round's merged update
        params, net_state, _, loss_fn, _, sketch_kw, workers = _resnet9_workload()
        import jax
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree

        from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
        from commefficient_tpu.federated.api import FederatedSession
        from commefficient_tpu.modes.config import ModeConfig

        d = ravel_pytree(params)[0].size
        rng = np.random.RandomState(0)
        n_examples = max(512, workers * LOCAL_BATCH * 4)
        x = rng.randn(n_examples, 32, 32, 3).astype(np.float32)
        y = rng.randint(0, 10, size=n_examples).astype(np.int32)
        train_set = FedDataset(
            x, y, shard_iid(n_examples, max(2 * workers, 8),
                            np.random.RandomState(1)))
        mode_cfg = ModeConfig(
            mode="sketch", d=d, momentum_type="virtual", error_type="virtual",
            topk_impl=os.environ.get("BENCH_TOPK_IMPL", "approx"),
            topk_recall=float(os.environ.get("BENCH_TOPK_RECALL", 0.99)),
            **sketch_kw,
        )
        session = FederatedSession(
            train_loss_fn=loss_fn, eval_loss_fn=loss_fn,
            params=jax.tree.map(jnp.copy, params),
            net_state=jax.tree.map(jnp.copy, net_state),
            mode_cfg=mode_cfg, train_set=train_set, num_workers=workers,
            local_batch_size=LOCAL_BATCH, weight_decay=5e-4, seed=0,
            split_compile=BENCH_ENGINE_COMPILE == "split",
        )
        quorum = max(workers * 3 // 4, 1)
        service = AggregationService(
            session,
            ServeConfig(quorum=quorum, deadline_s=8.0),
            traffic=TrafficGenerator(
                TraceConfig(population=train_set.num_clients, seed=0)),
        ).start()
        try:
            # submission-to-merge latency now comes from the obs registry
            # histogram the service itself maintains (serve_submit_to_merge_ms:
            # accept wall time -> the commit that published the round's
            # merge) — the ad-hoc submit-wrapping latency math this section
            # used to carry lives in the serving layer proper now
            src = service.source()
            base_count = service._latency.count
            t0 = _time.perf_counter()
            for _ in range(SERVE_ROUNDS):
                prep = src.next()
                session.commit_round(session.dispatch_round(prep, 0.01))
                # the runner's drain calls this hook; direct drivers do too
                src.on_committed(session.round)
            wall = _time.perf_counter() - t0
            n_merged = service._latency.count - base_count
            out["served_loop"] = {
                "quorum": quorum,
                "invited_per_round": workers,
                "wall_clock_updates_per_sec": round(
                    n_merged / max(wall, 1e-9), 2),
                "submit_to_merge_ms": {
                    **{k: v for k, v in service._latency.summary().items()
                       if k in ("p50", "p99")},
                    "n": n_merged,
                },
                "rounds_counters": service.assembler.counters(),
                "note": "obs registry histogram serve_submit_to_merge_ms; "
                        "first round carries the jit compile; p50 is the "
                        "honest steady-state figure, p99 the compile tail",
            }
        finally:
            service.close()

        # (d) pipelined vs serial (the always-on acceptance): the SAME warm
        # session through runner.run_loop — serial arm (next() runs the
        # whole invite/collect/close inline) vs --serve_pipeline (the
        # serve cycle on the always-on worker). Headline: sustained
        # merged-submissions/s, p99 submission-to-merge, and the
        # commit-to-dispatch gap server_idle_ms (the pipelined arm's must
        # collapse toward 0 — the acceptance criterion).
        from commefficient_tpu.federated.api import FedOptimizer
        from commefficient_tpu.runner.loop import RunnerConfig, run_loop

        def _pipeline_arm(pipelined: bool) -> dict:
            svc = AggregationService(
                session,
                ServeConfig(quorum=quorum, deadline_s=8.0,
                            pipeline=pipelined),
                traffic=TrafficGenerator(
                    TraceConfig(population=train_set.num_clients, seed=0)),
            ).start()
            try:
                merged0 = svc._latency.count
                t0 = _time.perf_counter()
                # max_inflight=1: drain (commit) every round, so the
                # commit-to-next-dispatch gap is MEASURED per round — a
                # deep in-flight chain would coalesce every commit into
                # one end-of-run drain and hide the idle the arms differ
                # by (the contrast, not the chain depth, is the point)
                stats = run_loop(
                    session, FedOptimizer(lambda e: 0.01, 1),
                    RunnerConfig(
                        total_rounds=session.round + SERVE_ROUNDS,
                        eval_every=10 ** 9, max_inflight=1),
                    source=svc.source())
                wall = _time.perf_counter() - t0
                merged = svc._latency.count - merged0
                return {
                    "merged_submissions_per_sec": round(
                        merged / max(wall, 1e-9), 2),
                    "submit_to_merge_ms": {
                        k: v for k, v in svc._latency.summary().items()
                        if k in ("p50", "p99")},
                    "server_idle_ms": round(stats.server_idle_ms, 3),
                    "server_idle_ms_max": round(
                        stats.server_idle_ms_max, 3),
                    "rounds": stats.rounds,
                }
            finally:
                svc.close()

        # serial first, pipelined second — both warm (section (c) above
        # already compiled the round programs on this session)
        serial = _pipeline_arm(False)
        pipelined = _pipeline_arm(True)
        out["pipelined_vs_serial"] = {
            "serial": serial,
            "pipelined": pipelined,
            "idle_collapse": round(
                serial["server_idle_ms"]
                - pipelined["server_idle_ms"], 3),
            "note": "server_idle_ms = mean commit-to-next-dispatch gap "
                    "(runner-measured, drain-per-round); the pipelined "
                    "arm's worker has the next round prepared when the "
                    "drain ends, so the gap is the queue pop, not the "
                    "serve cycle. submit_to_merge percentiles share the "
                    "registry window across arms (cumulative-run view); "
                    "the per-arm merged_submissions_per_sec and idle "
                    "figures are the A/B numbers",
        }
        # (e) the --serve_fastpath A/B (its own function so a CPU archive
        # run can produce just this section, like the r15 scale archive)
        out["fastpath_vs_slow"] = _fastpath_bench()
    except Exception as e:  # noqa: BLE001 — partial sections still report
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def _fastpath_bench() -> dict:
    """Zero-copy fast path A/B (--serve_fastpath): the SAME wire-payload
    trace + seed over the LOOPBACK SOCKET (real frames, real decode — the
    transport where the copy discipline differs), slow path vs pinned-ring
    + batched gauntlet + H2D overlap. Headlines per arm: submission-to-
    merge p50/p99 (percentile window reset between arms so each arm owns
    its figures) and bytes_touched_per_table — the
    serve_table_bytes_copied_total delta over accepted submissions (slow:
    decode copy + close-time stack copy = 2x table bytes; fast: the one
    ring-slot write). Never raises."""
    import time as _time

    import numpy as np

    try:
        import jax
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree

        from commefficient_tpu.data.fed_dataset import FedDataset, shard_iid
        from commefficient_tpu.federated.api import FederatedSession
        from commefficient_tpu.modes.config import ModeConfig
        from commefficient_tpu.serve import (
            AggregationService, ServeConfig, TraceConfig, TrafficGenerator,
        )
    except Exception as e:  # noqa: BLE001 — the skipped stanza IS the result
        return {"skipped": f"serve deps unavailable: {type(e).__name__}: {e}"}

    # 2 MiB/table (the flagship GPT-2-scale sketch dims): the fast path's
    # wins are BYTE wins — the close-time stack copy it deletes and the
    # H2D it overlaps — so the arms are compared where table bytes are the
    # round's dominant cost, not where fixed per-push overheads are
    rows, cols = 8, 65536
    din, dout, wire_workers = 16, 8, 8

    def _quad_loss(params, net_state, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        err = pred - jax.nn.one_hot(batch["y"], pred.shape[-1])
        mask = batch["mask"]
        count = jnp.maximum(mask.sum(), 1.0)
        per_ex = (err ** 2).sum(-1)
        return (per_ex * mask).sum() / count, {
            "net_state": net_state, "metrics": {}}

    def _wire_session():
        rs = np.random.RandomState(0)
        xw = rs.randn(256, din).astype(np.float32)
        w_true = rs.randn(din, dout).astype(np.float32)
        yw = (xw @ w_true).argmax(-1).astype(np.int32)
        wtrain = FedDataset(xw, yw, shard_iid(len(xw), 24,
                                              np.random.RandomState(1)))
        wparams = {"w": jnp.asarray(
            rs.randn(din, dout).astype(np.float32) * 0.1),
            "b": jnp.zeros(dout)}
        dw = ravel_pytree(wparams)[0].size
        return FederatedSession(
            train_loss_fn=_quad_loss, eval_loss_fn=_quad_loss,
            params=wparams, net_state={},
            mode_cfg=ModeConfig(mode="sketch", d=dw, k=8,
                                num_rows=rows, num_cols=cols,
                                momentum=0.9, momentum_type="virtual",
                                error_type="virtual"),
            train_set=wtrain, num_workers=wire_workers,
            local_batch_size=4, seed=0, wire_payloads=True,
        )

    def _fastpath_arm(fastpath: bool) -> dict:
        wsess = _wire_session()
        svc = AggregationService(
            wsess,
            ServeConfig(quorum=wire_workers, deadline_s=30.0,
                        transport="socket", payload="sketch",
                        fastpath=fastpath),
            traffic=TrafficGenerator(
                TraceConfig(population=wsess.train_set.num_clients,
                            seed=0)),
        ).start()
        try:
            reg = svc.registry
            src = svc.source()
            # warmup: each arm's first rounds pay their own XLA compiles
            # (the fast arm's chunk-concat + capacity-shaped scatter, the
            # slow arm's stack device_put + training step); the arms are
            # compared on steady-state rounds only
            for _ in range(2):
                prep = src.next()
                wsess.commit_round(wsess.dispatch_round(prep, 0.01))
                src.on_committed(wsess.round)
            reg.histogram("serve_submit_to_merge_ms").reset_window()
            bytes0 = reg.counter("serve_table_bytes_copied_total").value
            merged0 = svc._latency.count
            accepted0 = svc.queue.counters()["accepted"]
            t0 = _time.perf_counter()
            for _ in range(SERVE_ROUNDS):
                prep = src.next()
                wsess.commit_round(wsess.dispatch_round(prep, 0.01))
                src.on_committed(wsess.round)
            wall = _time.perf_counter() - t0
            accepted = svc.queue.counters()["accepted"] - accepted0
            dbytes = (reg.counter("serve_table_bytes_copied_total").value
                      - bytes0)
            return {
                "fastpath": fastpath,
                "merged_submissions_per_sec": round(
                    (svc._latency.count - merged0) / max(wall, 1e-9), 2),
                "submission_to_merge_ms": {
                    k: v for k, v in svc._latency.summary().items()
                    if k in ("p50", "p99")},
                "bytes_touched_per_table": round(
                    dbytes / max(accepted, 1), 1),
                "table_bytes": rows * cols * 4,
                "accepted": accepted,
                "gauntlet_batch_ms": (
                    reg.histogram("serve_gauntlet_batch_ms").summary()
                    if fastpath else None),
            }
        finally:
            svc.close()

    try:
        slow_arm = _fastpath_arm(False)
        fast_arm = _fastpath_arm(True)
    except Exception as e:  # noqa: BLE001 — partial sections still report
        return {"error": f"{type(e).__name__}: {e}"}
    return {
        "rounds": SERVE_ROUNDS,
        "rows_cols": [rows, cols],
        "invited_per_round": wire_workers,
        "slow": slow_arm,
        "fast": fast_arm,
        "bytes_touched_ratio": round(
            slow_arm["bytes_touched_per_table"]
            / max(fast_arm["bytes_touched_per_table"], 1e-9), 3),
        "note": "same trace, same seed, loopback socket; slow touches each "
                "accepted table's bytes twice on host (decode astype + "
                "close-time stack), fast once (the pinned ring-slot write) "
                "with the validation gauntlet batched and the H2D upload "
                "overlapping the open window. Both arms commit bitwise-"
                "identical params (pinned in tests/test_serve.py)",
    }


def _mesh_bench(rt_ms: float) -> dict:
    """Strong-scaling curve of the SPMD sharded round: the SAME global
    cohort (NUM_WORKERS clients) on 1, 2, 4, ... devices, per-device and
    aggregate updates/s per count, plus the analytic per-round cross-device
    traffic (sketch-table merge vs dense all-reduce — the reason the round
    scales: the merge ships r*c floats, not d). Uses the flagship workload
    dims; never raises."""
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    n = jax.device_count()
    if n < 2:
        return {"skipped": f"{n} device visible; the mesh section needs >= 2 "
                           "(run under a multi-chip mesh or "
                           "XLA_FLAGS=--xla_force_host_platform_device_count=8)"}
    out: dict = {"n_devices": n}
    try:
        from commefficient_tpu.federated import engine
        from commefficient_tpu.modes.config import ModeConfig
        from commefficient_tpu.parallel import mesh as meshlib
        from commefficient_tpu.sketch import csvec

        workload = _gpt2_workload if BENCH_MODEL == "gpt2" else _resnet9_workload
        params, net_state, batch, loss_fn, name, sketch_kw, workers = workload()
        d = ravel_pytree(params)[0].size
        mode_cfg = ModeConfig(
            mode="sketch", d=d, momentum_type="virtual", error_type="virtual",
            topk_impl=os.environ.get("BENCH_TOPK_IMPL", "approx"),
            topk_recall=float(os.environ.get("BENCH_TOPK_RECALL", 0.99)),
            **sketch_kw,
        )
        if (csvec._use_pallas(mode_cfg.sketch_spec)
                and os.environ.get("BENCH_MESH") != "1"):
            return {"skipped": "pallas engine routed; set BENCH_MESH=1 to "
                               "compile the Mosaic-bearing shard_map round"}
        counts = [c for c in (1, 2, 4, 8, 16, 32, 64, 128)
                  if c <= n and workers % c == 0]
        if len(counts) < 2:
            # no multi-device count divides the cohort: a "scaling" section
            # that measured no mesh must say so, not quietly bench 1 device
            return {"skipped": f"no device count in 2..{n} divides the "
                               f"cohort (BENCH_WORKERS={workers})"}
        out["workers"] = workers
        out["device_counts"] = counts
        scaling: dict = {}
        for c in counts:
            # same HBM bound as _make_step: gpt2 caps concurrent [d] grads
            # per shard (the chunk must divide the PER-SHARD cohort)
            if BENCH_MODEL == "gpt2":
                import math
                chunk = math.gcd(
                    int(os.environ.get("BENCH_CLIENT_CHUNK", 8)) or 8,
                    workers // c)
            else:
                chunk = 0
            cfg = engine.EngineConfig(
                mode=mode_cfg, weight_decay=5e-4, client_shards=c,
                client_chunk=chunk,
                on_nonfinite=os.environ.get("BENCH_ON_NONFINITE", "skip"),
            )
            if c == 1:
                step = jax.jit(engine.make_round_step(loss_fn, cfg),
                               donate_argnums=(0,))
                batch_c = batch
            else:
                mesh = meshlib.make_mesh(c)
                step = jax.jit(
                    engine.make_sharded_round_step(loss_fn, cfg, mesh),
                    donate_argnums=(0,))
                batch_c = meshlib.shard_client_batch(mesh, batch)
            state = engine.init_server_state(
                cfg, jax.tree.map(jnp.copy, params),
                jax.tree.map(jnp.copy, net_state))
            state, _, _ = step(state, batch_c, {}, jnp.float32(0.01),
                               jax.random.PRNGKey(0))
            _ = jax.device_get(state["round"] + jnp.int32(0))
            ms, state = _timed_chains(
                step, state, batch_c, MESH_CHAINS, CHAIN_LEN, rt_ms)
            round_ms = sorted(ms)[len(ms) // 2]
            scaling[str(c)] = {
                "round_ms": round(round_ms, 2),
                "updates_per_sec_aggregate": round(
                    workers / max(round_ms / 1e3, 1e-9), 2),
                "updates_per_sec_per_device": round(
                    workers / max(round_ms / 1e3, 1e-9) / c, 2),
            }
        out["scaling"] = scaling
        if "1" in scaling:
            base = scaling["1"]["round_ms"]
            out["speedup_vs_1_device"] = {
                c: round(base / max(s["round_ms"], 1e-9), 2)
                for c, s in scaling.items()
            }
        out["comm_per_round"] = meshlib.merge_comm_bytes(
            counts[-1], mode_cfg.num_rows, mode_cfg.num_cols, d)
        out["note"] = (
            "strong scaling at the fixed flagship cohort: each device "
            "reduces+sketches its client shard locally and the cross-device "
            "merge ships one r x c table (comm_per_round vs the dense [d] "
            "all-reduce a gradient-synchronous round would pay); "
            "updates_per_sec_per_device falling while aggregate rises means "
            "the fixed sketch-server step is amortizing, not the clients"
        )
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def run_bench(platform: str) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from commefficient_tpu.sketch import csvec

    _stage(f"claiming device(s) on platform={platform} ...")
    _stage(f"claimed: {jax.devices()}")
    workload = _gpt2_workload if BENCH_MODEL == "gpt2" else _resnet9_workload
    params, net_state, batch, loss_fn, name, sketch_kw, workers = workload()
    d = ravel_pytree(params)[0].size
    _stage(f"workload ready: {name}, d={d}, workers={workers}")

    engine, mode_cfg, cfg, step = _make_step(loss_fn, sketch_kw, d)
    # the step donates its input state, which would invalidate `params`
    # mid-run — give each state its own copy (scale check needs a second one)
    state = engine.init_server_state(
        cfg, jax.tree.map(jnp.copy, params), jax.tree.map(jnp.copy, net_state)
    )

    rt_ms = _tunnel_round_trip_ms()
    _stage(f"tunnel round-trip {rt_ms:.2f} ms; compiling round step "
           "(first call) ...")

    for i in range(WARMUP_ROUNDS):
        state, _, _ = step(state, batch, {}, jnp.float32(0.01), jax.random.PRNGKey(i))
    _ = jax.device_get(state["round"] + jnp.int32(0))
    _stage("compile + warmup done; timing chains ...")

    per_round_ms, state = _timed_chains(
        step, state, batch, NUM_CHAINS, CHAIN_LEN, rt_ms
    )
    _stage(f"chains done: per-round ms {sorted(round(m, 2) for m in per_round_ms)}")
    round_ms = sorted(per_round_ms)[len(per_round_ms) // 2]

    device_kind = jax.devices()[0].device_kind
    n_chips = jax.device_count()
    updates_per_sec_per_chip = workers / (round_ms / 1e3) / n_chips

    _stage("running XLA cost analysis ...")
    chunk_trips = (
        workers // cfg.client_chunk
        if cfg.client_chunk and workers > cfg.client_chunk else 1)
    flops, flops_note = _flops_per_round(step, state, batch, chunk_trips)
    _stage("kernel microbench ...")
    microbench = _kernel_microbench(platform, rt_ms)
    _stage(f"microbench: {microbench}")
    peak = next((p for k, p in _PEAK_BF16 if k in device_kind.lower()), None)
    achieved = flops / (round_ms / 1e3) if flops else None
    mfu = achieved / peak if (achieved and peak) else None

    result = {
        "metric": f"client-updates/sec/chip ({name}, mode=sketch, "
                  f"r={mode_cfg.num_rows} c={mode_cfg.num_cols} k={mode_cfg.k})",
        "value": round(updates_per_sec_per_chip, 2),
        "unit": "client-updates/sec/chip",
        # reference 0 = no comparable reference exists (tiny smoke size)
        "vs_baseline": (
            round(updates_per_sec_per_chip / REFERENCE_CLIENT_UPDATES_PER_SEC, 3)
            if REFERENCE_CLIENT_UPDATES_PER_SEC else 0.0),
        "vs_baseline_reference": {
            "client_updates_per_sec": REFERENCE_CLIENT_UPDATES_PER_SEC,
            "derivation": REFERENCE_DERIVATION,
        },
        "platform": platform,
        "device_kind": device_kind,
        "compute_dtype": BENCH_DTYPE,
        "sketch": {"rows": mode_cfg.num_rows, "cols": mode_cfg.num_cols,
                   "k": mode_cfg.k, "blocks": mode_cfg.num_blocks, "d": int(d),
                   "topk_impl": mode_cfg.topk_impl,
                   **({"topk_recall": mode_cfg.topk_recall,
                       "topk_provenance": (
                           "effective recall measured on-chip at these "
                           "workload dims: results/topk_recall_probe_r05.md"
                           if (int(d), mode_cfg.k) in _PROBED_TOPK_DIMS else
                           "effective recall NOT probed at these dims "
                           "(probe covers flagship/GPT-2 defaults: "
                           "results/topk_recall_probe_r05.md)")}
                      if mode_cfg.topk_impl in ("approx", "oversample")
                      else {})},
        # which accumulate/query implementation the round step itself compiled
        # (COMMEFFICIENT_NO_PALLAS=1 forces "oracle"; the microbench below
        # still times the Pallas kernels directly either way)
        "engine_sketch_path": (
            "pallas" if csvec._use_pallas(mode_cfg.sketch_spec) else "oracle"),
        # fused = one XLA program per round; split = Mosaic-isolating
        # two-program round (engine.make_split_round_step)
        "engine_compile": BENCH_ENGINE_COMPILE,
        "round_ms": round(round_ms, 2),
        "round_ms_percentiles": {
            "min": round(min(per_round_ms), 2),
            "median": round(round_ms, 2),
            "max": round(max(per_round_ms), 2),
            "chains": NUM_CHAINS, "chain_len": CHAIN_LEN,
        },
        "sync_method": "device_get(scalar) per chain, tunnel round-trip "
                       f"{round(rt_ms, 2)} ms subtracted",
        "flops_per_round_xla": flops,
        **({"flops_per_round_xla_note": flops_note} if flops_note else {}),
        "achieved_tflops": round(achieved / 1e12, 2) if achieved else None,
        "bf16_peak_tflops": round(peak / 1e12, 1) if peak else None,
        "mfu": round(mfu, 4) if mfu else None,
        "kernel_microbench": microbench,
        "pallas": _pallas_status(),
    }
    if BENCH_MODEL == "resnet9":
        result["flops_per_round_analytic"] = _analytic_resnet9_flops(
            workers, LOCAL_BATCH
        )
    if PHASE_TIMING:
        if (result["engine_sketch_path"] == "pallas"
                and os.environ.get("BENCH_PHASE_TIMING") != "1"):
            # the server chain would be a NEW Mosaic-bearing scan module — an
            # unproven compile shape on the wedge-prone chip, attempted AFTER
            # the main result exists but before the JSON prints. Opt in
            # explicitly (BENCH_PHASE_TIMING=1) to take that risk.
            result["phase_timing"] = {
                "skipped": "pallas engine routed; set BENCH_PHASE_TIMING=1 "
                           "to compile the Mosaic-bearing phase chains"}
        else:
            _stage("phase timing (client | sketch-server chains) ...")
            result["phase_timing"] = _phase_timing(loss_fn, cfg, state, batch, rt_ms)
            _stage(f"phase timing: {result['phase_timing']}")
    if SERVER_SPLIT:
        if (result["engine_sketch_path"] == "pallas"
                and os.environ.get("BENCH_SERVER_SPLIT") != "1"):
            # query_all/sketch_vec route Pallas when it's on — these chains
            # would be new Mosaic-bearing scan modules (same caveat as
            # phase_timing above); opt in explicitly to take that risk.
            result["server_split"] = {
                "skipped": "pallas engine routed; set BENCH_SERVER_SPLIT=1 "
                           "to compile the Mosaic-bearing op chains"}
        else:
            _stage("server split (accumulate | estimates | topk) ...")
            result["server_split"] = _server_split(mode_cfg, rt_ms)
            _stage(f"server split: {result['server_split']}")
    if BASELINE_BASIS:
        _stage("baseline basis (single-client f32 fwd+bwd) ...")
        result["vs_baseline_basis"] = _baseline_basis(rt_ms)
        _stage(f"baseline basis: {result['vs_baseline_basis']}")

    if SCALE_CHECK:
        _stage("scale check (2x workers) ...")
        # physical-consistency check: double the client count, round time
        # should roughly double (compute-bound vmap). A flat time would mean
        # the timing is still an async illusion. Workload-agnostic: every
        # batch leaf has the client axis leading.
        batch2 = jax.tree.map(lambda a: jnp.concatenate([a] * 2, axis=0), batch)
        state2 = engine.init_server_state(
            cfg, jax.tree.map(jnp.copy, params), jax.tree.map(jnp.copy, net_state)
        )
        for i in range(2):
            state2, _, _ = step(state2, batch2, {}, jnp.float32(0.01), jax.random.PRNGKey(i))
        _ = jax.device_get(state2["round"] + jnp.int32(0))
        ms2, _ = _timed_chains(step, state2, batch2, 2, CHAIN_LEN, rt_ms)
        ratio = sorted(ms2)[len(ms2) // 2] / round_ms
        result["scale_check"] = {
            "workers_x2_round_ms_ratio": round(ratio, 2),
            "plausible": bool(1.3 <= ratio <= 3.0),
        }
        if ratio < 1.3:
            # flat scaling has two honest readings — distinguish before
            # condemning the timing: the fixed server step (sketch algebra +
            # unsketch over d, independent of W) can dominate small cohorts.
            result["scale_check"]["note"] = (
                "ratio < 1.3: either async-illusion timing OR a "
                "server-dominated round (the sketch server step's cost is "
                "independent of W); phase_timing's client_ms vs server_ms "
                "distinguishes the two")

    if MESH_BENCH:
        _stage("mesh scaling (sharded round across devices) ...")
        result["mesh"] = _mesh_bench(rt_ms)
        _stage(f"mesh: {result['mesh']}")

    rl_nonfinite = 0
    if RUN_LOOP:
        if BENCH_MODEL == "resnet9":
            _stage("run-loop harness (sync vs async overlap) ...")
            rl = _run_loop_bench(round_ms)
            if "obs" in rl:
                # tracing overhead is its own top-level section (the obs
                # layer is cross-cutting, not a run-loop detail)
                result["obs"] = rl.pop("obs")
            result["run_loop"] = rl
            _stage(f"run_loop: {rl}")
            if "async" in rl:
                # the end-to-end headline pair: what a real training loop
                # delivers (vs `value`, the chained compiled-round ceiling)
                result["wall_clock_updates_per_sec"] = (
                    rl["async"]["wall_clock_updates_per_sec"])
                result["host_overhead_ms"] = rl["async"]["host_overhead_ms"]
                rl_nonfinite = rl.get("nonfinite_rounds", 0)
        else:
            result["run_loop"] = {
                "skipped": "run-loop section measures the flagship resnet9 "
                           "workload (BENCH_MODEL=resnet9)"}
    if HEALTH_BENCH:
        if BENCH_MODEL == "resnet9":
            _stage("obs.health (estimator overhead + recall-proxy "
                   "validation) ...")
            health_arm = _health_bench()
            result.setdefault("obs", {})["health"] = health_arm
            _stage(f"obs.health: {health_arm}")
        else:
            result.setdefault("obs", {})["health"] = {
                "skipped": "obs.health section measures the flagship "
                           "resnet9 workload (BENCH_MODEL=resnet9)"}
    if SKETCH_PATH_BENCH:
        if BENCH_MODEL == "resnet9":
            _stage("sketch_path (ravel vs layerwise accumulation) ...")
            result["sketch_path"] = _sketch_path_bench(round_ms)
            _stage(f"sketch_path: {result['sketch_path']}")
        else:
            result["sketch_path"] = {
                "skipped": "sketch_path section measures the flagship "
                           "resnet9 workload (BENCH_MODEL=resnet9); at "
                           "GPT-2 dims run it with BENCH_MODEL=resnet9 "
                           "overridden dims or on-chip"}
    if SERVE_BENCH:
        if BENCH_MODEL == "resnet9":
            _stage("serve (ingest throughput / O(1) client state / "
                   "submission-to-merge latency) ...")
            result["serve"] = _serve_bench()
            _stage(f"serve: {result['serve']}")
        else:
            result["serve"] = {
                "skipped": "serve section measures the flagship resnet9 "
                           "workload (BENCH_MODEL=resnet9)"}
    if SCALE_BENCH:
        _stage("scale (transport concurrency ramp + edge-tree vs flat "
               "merge wall-clock at W=256 + process-shard strong scaling "
               "+ 100k-connection loadgen ramp) ...")
        result["scale"] = _scale_bench()
        _stage(f"scale: {result['scale']}")
    else:
        result["scale"] = {
            "skipped": "gated off (BENCH_SCALE=0 default — opens thousands "
                       "of loopback sockets and raises RLIMIT_NOFILE); set "
                       "BENCH_SCALE=1 [+ BENCH_SCALE_CONNS/_ROUNDS/"
                       "BENCH_LOADGEN_CONNS] to run the threaded-vs-"
                       "eventloop concurrency ramp, the edge-tree vs flat "
                       "merge arm, the process-shard strong-scaling curve, "
                       "and the 100k-connection loadgen ramp"}
    if BYZANTINE_BENCH:
        if BENCH_MODEL == "resnet9":
            _stage("byzantine (attack kind x merge policy accuracy + "
                   "merge-policy overhead) ...")
            result["byzantine"] = _byzantine_bench()
            _stage(f"byzantine: {result['byzantine']}")
        else:
            result["byzantine"] = {
                "skipped": "byzantine section measures the flagship resnet9 "
                           "workload (BENCH_MODEL=resnet9)"}
    else:
        result["byzantine"] = {
            "skipped": "gated off (BENCH_BYZANTINE=0, or the CPU fallback's "
                       "default — 12 arms x two compiles each); set "
                       "BENCH_BYZANTINE=1 [+ BENCH_BYZANTINE_ROUNDS] to run "
                       "the attack-kind x merge-policy grid"}

    # chaos runs are benchmarkable: what the resilience layer absorbed while
    # this process produced the numbers above (nonzero only under
    # BENCH_FAULT_PLAN or real flakes)
    from commefficient_tpu.resilience import retry_counts
    from commefficient_tpu.utils import checkpoint as _ckpt

    rl_cohort = (result.get("run_loop") or {}).get("cohort", {})
    result["resilience"] = {
        "nonfinite_rounds": rl_nonfinite,
        "retries": retry_counts(),
        "ckpt_save_verify_failures": _ckpt.save_verify_failures(),
        # cohort-level degradation absorbed by the run-loop arms (masked
        # clients, quarantined clients, degraded rounds, requeue depth)
        "clients_dropped": rl_cohort.get("clients_dropped", 0),
        "clients_quarantined": rl_cohort.get("clients_quarantined", 0),
        "degraded_rounds": rl_cohort.get("degraded_rounds", 0),
        "requeue_depth_max": rl_cohort.get("requeue_depth_max", 0),
        "attacks_injected": rl_cohort.get("attacks_injected", 0),
        **({"fault_plan": BENCH_FAULT_PLAN} if BENCH_FAULT_PLAN else {}),
    }
    return result


def _shrink_for_cpu():
    """The flagship dims are sized for a TPU chip; on the CPU fallback shrink
    anything the env didn't pin so the script still finishes in minutes."""
    g = globals()
    for name, small in [("NUM_WORKERS", 8), ("CHAIN_LEN", 3), ("NUM_CHAINS", 2),
                        ("WARMUP_ROUNDS", 1), ("MICROBENCH_D", 2_000_000),
                        ("MICRO_CHAIN", 3), ("SKETCH_COLS", 65_536),
                        ("TOPK", 8_192), ("PHASE_CHAIN", 2),
                        ("RUN_LOOP_ROUNDS", 6), ("SERVE_ROUNDS", 4),
                    ("BYZANTINE_ROUNDS", 6)]:
        env_name = {"NUM_WORKERS": "BENCH_WORKERS", "CHAIN_LEN": "BENCH_CHAIN_LEN",
                    "NUM_CHAINS": "BENCH_CHAINS", "WARMUP_ROUNDS": "BENCH_WARMUP",
                    "MICROBENCH_D": "BENCH_MICRO_D",
                    "MICRO_CHAIN": "BENCH_MICRO_CHAIN",
                    "SKETCH_COLS": "BENCH_COLS", "TOPK": "BENCH_TOPK",
                    "PHASE_CHAIN": "BENCH_PHASE_CHAIN",
                    "RUN_LOOP_ROUNDS": "BENCH_RUN_LOOP_ROUNDS",
                    "SERVE_ROUNDS": "BENCH_SERVE_ROUNDS",
                    "BYZANTINE_ROUNDS": "BENCH_BYZANTINE_ROUNDS"}[name]
        if env_name not in os.environ:
            g[name] = small
    if "BENCH_SCALE_CHECK" not in os.environ:
        g["SCALE_CHECK"] = False
    if "BENCH_BASELINE_BASIS" not in os.environ:
        # ~20 ResNet-9 fwd+bwd executions for a number only meaningful on-chip
        g["BASELINE_BASIS"] = False
    if "BENCH_PHASE_TIMING" not in os.environ:
        # two extra split-engine compiles — minutes on a 1-core CPU fallback
        g["PHASE_TIMING"] = False
    if "BENCH_SERVER_SPLIT" not in os.environ:
        g["SERVER_SPLIT"] = False  # four more chains; on-chip question only
    if "BENCH_BYZANTINE" not in os.environ:
        # 12 arms x two compiled programs each — tens of minutes on the CPU
        # fallback; set BENCH_BYZANTINE=1 (+ BENCH_BYZANTINE_ROUNDS) to
        # opt in there, on-chip it runs by default
        g["BYZANTINE_BENCH"] = False


def main():
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        platform = "cpu"  # explicitly pinned; no probe needed
    else:
        _stage("probing backend in subprocess ...")
        platform = _probe_backend()
        _stage(f"backend probe -> {platform}")
    if platform is None or platform == "cpu":
        _force_cpu()
        platform = "cpu"
        _shrink_for_cpu()
    try:
        result = run_bench(platform)
    except Exception as e:
        # Last-resort: never exit without a JSON line. Retry once on CPU if
        # the failure happened on an accelerator backend.
        print(f"# bench failed on {platform}: {type(e).__name__}: {e}", flush=True)
        if platform != "cpu" and os.environ.get("BENCH_NO_RETRY") != "1":
            try:
                env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_NO_RETRY="1")
                rerun = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                       env=env, timeout=3600)
                if rerun.returncode == 0:
                    return
            except Exception as retry_e:  # timeout etc. — fall through to JSON
                print(f"# cpu retry failed: {type(retry_e).__name__}", flush=True)
        print(json.dumps({
            "metric": "client-updates/sec/chip (CIFAR-10 ResNet-9, mode=sketch)",
            "value": 0.0,
            "unit": "client-updates/sec/chip",
            "vs_baseline": 0.0,
            "platform": platform,
            "error": f"{type(e).__name__}: {e}",
        }))
        return
    print(json.dumps(result))


if __name__ == "__main__":
    main()
